"""Perfetto/Chrome-trace export contract: valid JSON, monotone
timestamps, span/point/fault mapping, and THE acceptance pin — a
trace_id flow joining an enqueue point, a flush span, and a retry event
from a real serve run's streamed timeline (ISSUE 10)."""

import json
import os
import subprocess
import sys

import numpy as np

from ft_sgemm_tpu.cli import main as cli_main
from ft_sgemm_tpu.telemetry import traceview
from ft_sgemm_tpu.telemetry.timeline import TimelineRecorder

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_synthetic_timeline(path):
    rec = TimelineRecorder(str(path))
    with rec.span("import_jax", kind="compile"):
        pass
    rec.point("serve", "enqueue", trace_id="t1", request_id=1,
              bucket="B128")
    rec.point("serve", "enqueue", trace_id="t2", request_id=2,
              bucket="B128")
    with rec.span("serve[B128]", kind="stage",
                  trace_ids=["t1", "t2"]) as info:
        rec.point("serve", "retry", trace_id="t1", bucket="B128",
                  attempt=1)
        info["value"] = {"batch": 2}
    rec.point("heartbeat", "beat")
    rec.point("kill", "deadline reached")
    # An in-flight span: started, never ended (the kill signature).
    rec._write({"kind": "stage", "name": "ft_huge", "phase": "start",
                "t": 9e9})
    rec.close()


def _flow_hops(trace, trace_id):
    return [(e["ph"], e["args"]["hop"]) for e in trace["traceEvents"]
            if e.get("id") == trace_id and e.get("cat") == "serve.flow"]


def test_trace_is_valid_json_with_monotone_timestamps(tmp_path):
    tl = tmp_path / "run.timeline.jsonl"
    _write_synthetic_timeline(tl)
    trace, out_path = traceview.export_trace(str(tl))
    # Valid JSON on disk, loadable round-trip.
    loaded = json.loads(open(out_path).read())
    assert loaded["traceEvents"]
    evs = trace["traceEvents"]
    # Monotone timestamps (metadata first), all non-negative.
    body = [e["ts"] for e in evs if e["ph"] != "M"]
    assert body == sorted(body)
    assert all(ts >= 0 for ts in body)
    # Every event carries the Chrome-trace required fields.
    for e in evs:
        assert {"ph", "pid", "tid", "ts", "name"} <= set(e)
    meta = trace["otherData"]
    assert meta["spans"] == 2
    assert meta["in_flight"] == 1
    assert meta["dropped"] == 0


def test_span_point_and_kill_mapping(tmp_path):
    tl = tmp_path / "run.timeline.jsonl"
    _write_synthetic_timeline(tl)
    trace, _ = traceview.export_trace(str(tl))
    evs = trace["traceEvents"]
    by_name = {}
    for e in evs:
        by_name.setdefault(e["name"], []).append(e)
    # Completed spans are "X" complete events with duration.
    compile_span = by_name["import_jax"][0]
    assert compile_span["ph"] == "X" and compile_span["dur"] >= 1
    flush = by_name["serve[B128]"][0]
    assert flush["ph"] == "X"
    assert flush["args"]["trace_ids"] == ["t1", "t2"]
    # In-flight span -> unmatched "B" (renders as running to trace end).
    assert by_name["ft_huge"][0]["ph"] == "B"
    assert by_name["ft_huge"][0]["args"]["in_flight"] is True
    # Kill markers -> process-scoped instants.
    kill = by_name["KILL: deadline reached"][0]
    assert kill["ph"] == "i" and kill["s"] == "p"
    # Track names are declared as metadata.
    threads = {e["args"]["name"] for e in evs
               if e["ph"] == "M" and e["name"] == "thread_name"}
    assert {"stage", "compile", "serve", "faults"} <= threads


def test_flow_join_and_fault_event_merge(tmp_path):
    tl = tmp_path / "run.timeline.jsonl"
    _write_synthetic_timeline(tl)
    # A fault-event JSONL joins the flow via extra.trace_id; torn and
    # foreign lines are skipped; an event without ts is counted dropped.
    records = [json.loads(ln) for ln in open(tl) if ln.strip()]
    ev_path = tmp_path / "events.jsonl"
    with open(ev_path, "w") as fh:
        fh.write(json.dumps({
            "outcome": "corrected", "op": "serve_gemm",
            "ts": records[2]["t"] + 0.001, "tiles": [[1, 2]],
            "residual": 42.0, "extra": {"trace_id": "t1"}}) + "\n")
        fh.write(json.dumps({"outcome": "clean", "op": "gemm"}) + "\n")
        fh.write("torn {{{\n")
        fh.write("not json at all\n")
    trace = traceview.build_trace(
        traceview._read_timeline(str(tl)),
        traceview._read_fault_events(str(ev_path)))
    hops = _flow_hops(trace, "t1")
    assert [h[0] for h in hops][0] == "s"
    assert [h[0] for h in hops][-1] == "f"
    names = [h[1] for h in hops]
    assert names.index("enqueue") < names.index("flush")
    assert "detect" in names and "retry" in names
    # t2 never retried: enqueue + flush only, still a drawable 2-hop flow.
    assert len(_flow_hops(trace, "t2")) == 2
    # The fault instant landed with its tile args on the faults track.
    fault = [e for e in trace["traceEvents"]
             if e["name"] == "serve_gemm:corrected"][0]
    assert fault["args"]["tiles"] == [[1, 2]]
    # The no-ts event was dropped, named in the counts.
    assert trace["otherData"]["dropped"] == 1


def test_hostile_records_never_raise():
    trace = traceview.build_trace(
        [{"kind": "stage"}, {"not": "a record"}, 7, None,
         {"kind": "stage", "name": "x", "phase": "end", "t": "wat"},
         {"kind": "serve", "name": "enqueue", "phase": "point"}],
        [{"outcome": "clean"}, "junk", {"ts": None}])
    json.dumps(trace)
    assert trace["otherData"]["dropped"] >= 2


def test_merged_multi_rank_spans_never_alias():
    """Satellite bugfix pin (ISSUE 20): two ranks emitting IDENTICAL
    span names must pair within their own process — rank 1's end must
    never close rank 0's still-open span in a merged trace."""
    r2s = {"kind": "stage", "name": "program:smoke", "phase": "start",
           "t": 100.0, "_pid": 2}
    r2e = {"kind": "stage", "name": "program:smoke", "phase": "end",
           "t": 103.0, "_pid": 2}
    r3s = {"kind": "stage", "name": "program:smoke", "phase": "start",
           "t": 100.5, "_pid": 3}
    r3e = {"kind": "stage", "name": "program:smoke", "phase": "end",
           "t": 101.0, "_pid": 3}
    # Interleaved in the aliasing order: start0, start1, end1, end0.
    trace = traceview.build_trace([r2s, r3s, r3e, r2e])
    meta = trace["otherData"]
    assert meta["spans"] == 2 and meta["in_flight"] == 0
    assert meta["processes"] == 3  # implicit PID + the two ranks
    durs = {e["pid"]: e["dur"] for e in trace["traceEvents"]
            if e["ph"] == "X" and e["name"] == "program:smoke"}
    # Each rank's span keeps ITS OWN duration, not its neighbour's.
    assert durs[3] == 500000 and durs[2] == 3000000, durs


def test_killed_rank_in_flight_span_stays_its_own():
    trace = traceview.build_trace([
        {"kind": "stage", "name": "program:smoke", "phase": "start",
         "t": 10.0, "_pid": 2},
        {"kind": "stage", "name": "program:smoke", "phase": "start",
         "t": 10.1, "_pid": 3},
        # pid 2 completes; pid 3 was killed mid-span — its bar must
        # stay on ITS process row, not swallow the completed one's end.
        {"kind": "stage", "name": "program:smoke", "phase": "end",
         "t": 12.0, "_pid": 2},
    ])
    assert trace["otherData"]["spans"] == 1
    assert trace["otherData"]["in_flight"] == 1
    b = [e for e in trace["traceEvents"] if e["ph"] == "B"]
    assert b and b[0]["pid"] == 3


def test_merge_fleet_namespaces_skew_corrects_and_flows(tmp_path):
    """merge_fleet contract on a synthetic workdir: rank-namespaced
    names (never doubled), timestamps shifted by minus the dispatcher's
    measured skew, one trace_id flowing across process rows in the
    corrected order."""
    wd = tmp_path / "fleet"
    (wd / "rank0").mkdir(parents=True)
    (wd / "rank1").mkdir()
    base, skew = 1000.0, 5.0

    def w(path, rows):
        with open(path, "w", encoding="utf-8") as fh:
            for r in rows:
                fh.write(json.dumps(r) + "\n")

    w(wd / "fleet.timeline.jsonl",
      [{"kind": "fleet", "name": "spawn:rank1", "phase": "point",
        "t": base}])
    w(wd / "rank0" / "timeline.jsonl",
      [{"kind": "stage", "name": "program:trace", "phase": "start",
        "t": base},
       {"kind": "fleet", "name": "submit_host1", "phase": "point",
        "t": base + 0.5, "trace_id": "tx"},
       {"kind": "stage", "name": "program:trace", "phase": "end",
        "t": base + 2.0}])
    # The remote clock runs 5s AHEAD: its records carry wall t + skew.
    w(wd / "rank1" / "timeline.jsonl",
      [{"kind": "stage", "name": "program:trace", "phase": "start",
        "t": base + skew},
       {"kind": "fleet", "name": "rank1:execute", "phase": "point",
        "t": base + 1.0 + skew, "trace_id": "tx"},
       {"kind": "fleet", "name": "rank1:retry", "phase": "point",
        "t": base + 1.5 + skew, "trace_id": "tx"}])
    (wd / "rank0" / "result.json").write_text(json.dumps(
        {"serve": {"dispatcher": {"per_host": {
            "1": {"clock_skew_seconds": skew}}}}}), encoding="utf-8")

    trace, path = traceview.merge_fleet(str(wd))
    assert path == str(wd / "fleet.trace.json")
    meta = trace["otherData"]
    assert meta["ranks"] == [0, 1]
    assert meta["clock_skew_seconds"] == {"1": 5.0}
    assert meta["cross_process_flows"] == 1
    ev = trace["traceEvents"]
    hops = [e for e in ev
            if e.get("cat") == "serve.flow" and e.get("id") == "tx"]
    assert [h["args"]["hop"] for h in hops] == [
        "rank0:submit_host1", "rank1:execute", "rank1:retry"]
    assert [h["ph"] for h in hops] == ["s", "t", "f"]
    assert len({h["pid"] for h in hops}) == 2
    # Skew-corrected: execute lands 0.5s after submit on the SHARED
    # clock, not 5.5s on the remote's fast clock.
    assert hops[1]["ts"] - hops[0]["ts"] == 500000
    names = {e["name"] for e in ev if e["ph"] != "M"}
    assert "rank0:program:trace" in names
    assert "rank1:program:trace" in names
    assert not any(n.startswith("rank1:rank1:") for n in names)


def test_acceptance_serve_run_flow_joins_enqueue_flush_retry(tmp_path,
                                                            rng):
    """ISSUE 10 acceptance: `cli trace-export` of a REAL serve run
    yields a Chrome-trace JSON where at least one trace_id flow connects
    an enqueue point, a flush span, and a retry event — driven through
    the actual engine (an adversarial request forces the bucket-scoped
    retry ladder), not a synthetic timeline."""
    from ft_sgemm_tpu.serve import ServeEngine, ServeRequest, \
        default_bucket_set

    tl_path = str(tmp_path / "serve.timeline.jsonl")
    eng = ServeEngine(default_bucket_set((128, 256)), max_batch=1,
                      max_wait=0.01, retry_backoff=0.0, timeline=tl_path)
    eng.start()
    try:
        req = ServeRequest(
            a=rng.standard_normal((200, 200)).astype(np.float32),
            b=rng.standard_normal((200, 200)).astype(np.float32),
            variant="adversarial")
        res = eng.submit(req).result(timeout=120.0)
        assert res.retries >= 1 and res.ok
        trace_id = res.trace_id
    finally:
        eng.close()

    out_path = str(tmp_path / "serve.trace.json")
    rc = cli_main(["cli", "trace-export", tl_path, f"--out={out_path}"])
    assert rc == 0
    trace = json.loads(open(out_path).read())
    hops = [(e["ph"], e["args"]["hop"])
            for e in trace["traceEvents"]
            if e.get("id") == trace_id and e.get("cat") == "serve.flow"]
    names = [h[1] for h in hops]
    assert "enqueue" in names, hops
    assert "flush" in names, hops
    assert "retry" in names, hops
    assert names.index("enqueue") < names.index("flush") \
        < names.index("retry")
    assert hops[0][0] == "s" and hops[-1][0] == "f"
    # The flush hop anchors INSIDE the batch slice carrying the trace id.
    flush_slices = [e for e in trace["traceEvents"]
                    if e["ph"] == "X" and trace_id in
                    (e.get("args", {}).get("trace_ids") or [])]
    assert flush_slices, "no batch slice names the trace"


def test_cli_trace_export_exit_codes(tmp_path, capsys):
    # Missing timeline -> 2.
    assert cli_main(["cli", "trace-export",
                     str(tmp_path / "missing.jsonl")]) == 2
    # Readable but empty timeline -> 1 (named, not a silent empty file).
    empty = tmp_path / "empty.timeline.jsonl"
    empty.write_text("not a record\n")
    assert cli_main(["cli", "trace-export", str(empty)]) == 1
    # Success prints the summary and defaults the output path.
    tl = tmp_path / "ok.timeline.jsonl"
    _write_synthetic_timeline(tl)
    capsys.readouterr()
    assert cli_main(["cli", "trace-export", str(tl)]) == 0
    out = capsys.readouterr().out
    assert "request flows" in out
    assert (tmp_path / "ok.trace.json").exists()


def test_module_is_loadable_without_the_package(tmp_path):
    """timeline.py discipline: stdlib-only, loadable by file path from a
    process that never imports jax."""
    tl = tmp_path / "run.timeline.jsonl"
    _write_synthetic_timeline(tl)
    code = """
import importlib.util, sys
assert "jax" not in sys.modules
spec = importlib.util.spec_from_file_location("tv", {mod_path!r})
mod = importlib.util.module_from_spec(spec)
spec.loader.exec_module(mod)
assert "jax" not in sys.modules, "traceview.py pulled jax in"
trace, path = mod.export_trace({tl_path!r})
assert trace["traceEvents"]
print("OK")
""".format(mod_path=os.path.join(REPO, "ft_sgemm_tpu", "telemetry",
                                 "traceview.py"),
           tl_path=str(tl))
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "OK" in proc.stdout
