"""Adaptive variance-bound thresholds + the fp8/int8 kernel family.

Pins the ISSUE-7 contract points:

1. **Default is untouched** — ``threshold="static"`` (the named spelling)
   lowers to BYTE-IDENTICAL HLO vs the numeric default per strategy;
   ``threshold="adaptive"`` genuinely changes the program (the
   tests/test_telemetry.py pinning technique).
2. **Variance-bound math** — the host twin
   (``analysis.adaptive_threshold_estimate``) equals a brute-force
   moment evaluation of the shared formula
   (``ops.common.variance_bound_threshold``), scales ~quadratically with
   input scale, and caps finite.
3. **Adaptive cadence/strategy sweeps** (mirroring test_encode_mxu):
   dense injection corrected at ``check_every in {1, 2, nk}`` across
   strategies and dtypes, clean runs detect ZERO at every input scale.
4. **Low-precision variants** — fp8_e4m3 (f32 accumulation) and int8
   (int32-exact accumulation) verify against the dtype-matched XLA
   oracle; int8 clean residuals are exactly zero and unit faults are
   detectable.
5. **Legality** — the per-dtype constraints raise loud ValueErrors;
   the vmem model carries the adaptive/exact footprint terms.
6. **Tuner** — ``thr=`` and the dtype join the cache key; schema-2
   caches MISS cleanly after the bump (re-tune, never raise/mis-key).
7. **ROC** — adaptive Pareto-dominates the calibrated static threshold,
   with zero clean-run false positives.
"""

import json

import jax
import numpy as np
import pytest

from ft_sgemm_tpu import InjectionSpec, make_ft_sgemm, sgemm_reference
from ft_sgemm_tpu.configs import (
    IN_DTYPES,
    THRESHOLD_MODES,
    KernelShape,
    aug_rows,
    canonical_in_dtype,
    check_kernel_legality,
)
from ft_sgemm_tpu.utils import generate_random_matrix, verify_matrix

ALPHA, BETA = 1.0, -1.5
TILE = KernelShape("t128", 128, 128, 128, (0,) * 7)
STRATEGIES = ("rowcol", "global", "weighted", "fused")


def _inputs(m, n, k, seed=10):
    rng = np.random.default_rng(seed)
    return (
        generate_random_matrix(m, k, rng=rng),
        generate_random_matrix(n, k, rng=rng),
        generate_random_matrix(m, n, rng=rng),
    )


def _int_inputs(m, n, k, seed=10, scale=9):
    rng = np.random.default_rng(seed)
    a = np.clip(np.round(rng.standard_normal((m, k)) * scale / 2), -127,
                127).astype(np.float32)
    b = np.clip(np.round(rng.standard_normal((n, k)) * scale / 2), -127,
                127).astype(np.float32)
    c = generate_random_matrix(m, n, rng=rng)
    return a, b, c


def _lower(fn, a, b, c):
    return jax.jit(lambda a, b, c: fn(a, b, c).c).lower(a, b, c).as_text()


# -- 1. default-path pin: threshold="static" is byte-for-byte the default ----


@pytest.mark.parametrize("strategy", ["rowcol", "global", "weighted"])
def test_static_threshold_spelling_hlo_byte_identical(strategy):
    a, b, c = _inputs(256, 128, 512)
    default = make_ft_sgemm(TILE, alpha=ALPHA, beta=BETA, strategy=strategy)
    named = make_ft_sgemm(TILE, alpha=ALPHA, beta=BETA, strategy=strategy,
                          threshold="static")
    assert _lower(default, a, b, c) == _lower(named, a, b, c), (
        f"{strategy}: threshold='static' changed the default HLO")
    adaptive = make_ft_sgemm(TILE, alpha=ALPHA, beta=BETA, strategy=strategy,
                             threshold="adaptive")
    assert _lower(adaptive, a, b, c) != _lower(default, a, b, c), (
        f"{strategy}: threshold='adaptive' lowered to the static program —"
        " the axis did nothing")


def test_unknown_threshold_mode_rejected():
    with pytest.raises(ValueError, match="threshold"):
        make_ft_sgemm(TILE, threshold="dynamic")
    assert THRESHOLD_MODES == ("static", "auto", "adaptive")


def test_threshold_mode_attribute_and_op_name():
    ft = make_ft_sgemm(TILE, strategy="rowcol", threshold="adaptive")
    assert ft.threshold_mode == "adaptive"
    assert "adaptive" in ft.__name__
    assert make_ft_sgemm(TILE, strategy="rowcol").threshold_mode == "static"
    assert make_ft_sgemm(
        TILE, strategy="rowcol", threshold="auto").threshold_mode == "auto"


# -- 2. variance-bound math vs brute-force per-tile moments ------------------


def test_variance_bound_matches_brute_force_moments(rng):
    from ft_sgemm_tpu.analysis import adaptive_threshold_estimate
    from ft_sgemm_tpu.ops.common import (
        NOISE_C_BIAS, NOISE_C_RAND, variance_bound_threshold)

    bm = bn = 128
    k = 256
    a = rng.standard_normal((bm, k)).astype(np.float32) * 3.0
    b = rng.standard_normal((bn, k)).astype(np.float32) * 0.5
    thr, variance = adaptive_threshold_estimate(a, b, bm=bm, bn=bn,
                                                margin=8.0)
    # Brute force: the same formula from directly computed moments.
    s_a1 = float(np.sum(a, dtype=np.float64))
    s_a2 = float(np.sum(a.astype(np.float64) ** 2))
    s_b1 = float(np.sum(b, dtype=np.float64))
    s_b2 = float(np.sum(b.astype(np.float64) ** 2))
    n_a = n_b = float(bm * k)
    t_ab = float(k * max(bm, bn))
    eps = float(np.finfo(np.float32).eps)
    sigma = np.sqrt((s_a2 / n_a) * (s_b2 / n_b))
    mu = (s_a1 / n_a) * (s_b1 / n_b)
    expect = 8.0 * eps * (
        NOISE_C_RAND * np.sqrt(t_ab) * sigma
        + NOISE_C_BIAS * np.log2(t_ab) * t_ab * abs(mu))
    assert thr == pytest.approx(expect, rel=1e-6)
    assert variance == pytest.approx((s_a2 / n_a) * (s_b2 / n_b), rel=1e-6)
    # The shared implementation is the one the kernels call.
    direct = variance_bound_threshold(
        s_a1, s_a2, s_b1, s_b2, n_a=n_a, n_b=n_b, t_ab=t_ab,
        log2_t=float(np.log2(t_ab)), margin=8.0, xp=np)
    assert float(direct) == pytest.approx(expect, rel=1e-6)


def test_variance_bound_scales_with_operand_variance(rng):
    from ft_sgemm_tpu.analysis import adaptive_threshold_estimate

    a = rng.standard_normal((128, 256)).astype(np.float32)
    b = rng.standard_normal((128, 256)).astype(np.float32)
    thr1, _ = adaptive_threshold_estimate(a, b, bm=128, bn=128)
    thr10, _ = adaptive_threshold_estimate(a * 10, b * 10, bm=128, bn=128)
    # Both operands scaled by s -> sigma scales by s^2 (mu term rides
    # along at the same rate): the bound tracks operand variance.
    assert thr10 == pytest.approx(100.0 * thr1, rel=0.05)


def test_variance_bound_saturates_finite():
    from ft_sgemm_tpu.ops.common import variance_bound_threshold

    huge = float(np.finfo(np.float32).max)
    thr = variance_bound_threshold(0.0, huge, 0.0, huge, n_a=1.0, n_b=1.0,
                                   t_ab=1e30, log2_t=100.0, margin=8.0,
                                   xp=np)
    assert np.isfinite(thr)


# -- 3. adaptive cadence/strategy sweeps (mirroring test_encode_mxu) ---------


@pytest.mark.parametrize("check_every", [1, 2, 4])  # 4 == nk at k=512
@pytest.mark.parametrize("strategy", STRATEGIES)
def test_adaptive_cadence_sweep_multi_fault(strategy, check_every):
    """Dense injection under threshold="adaptive": correcting strategies
    restore the oracle exactly and report zero uncorrectable; the
    detect-only global strategy counts every fault event."""
    m = n = 128
    k = 512  # nk = 4 at bk=128
    a, b, c = _inputs(m, n, k, seed=7)
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    ft = make_ft_sgemm(TILE, alpha=ALPHA, beta=BETA, strategy=strategy,
                       threshold="adaptive", check_every=check_every)
    res = ft(a, b, c, inject=inj)
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    if strategy == "global":
        assert int(res.num_detected) == -(-4 // check_every)
        assert int(res.num_uncorrectable) == int(res.num_detected)
        return
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok, (f"{strategy}/adaptive/ce={check_every}: {nbad}"
                " corrupted elements survived")
    assert int(res.num_detected) == 4
    assert int(res.num_uncorrectable) == 0


@pytest.mark.parametrize("encode", ["vpu", "mxu"])
@pytest.mark.parametrize("strategy", ["rowcol", "global"])
def test_adaptive_tiny_faults_both_encodes(strategy, encode):
    """Adaptive thresholds catch magnitude-5 faults (5 orders under the
    reference 9500) under BOTH encodes — the moment statistics ride the
    VPU whichever unit builds the expected checksums."""
    a, b, c = _inputs(128, 128, 512, seed=17)
    inj = InjectionSpec(enabled=True, every=1, magnitude=5.0)
    res = make_ft_sgemm(TILE, alpha=ALPHA, beta=BETA, strategy=strategy,
                        encode=encode, threshold="adaptive")(
        a, b, c, inject=inj)
    assert int(res.num_detected) == 4
    if strategy != "global":
        want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
        ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
        assert ok, f"{nbad} tiny faults survived adaptive/{encode}"
        assert int(res.num_uncorrectable) == 0


@pytest.mark.parametrize("scale", [0.1, 1.0, 16.0])
def test_adaptive_clean_runs_zero_fp_across_scales(scale, rng):
    """The per-tile threshold tracks operand variance: clean runs detect
    ZERO at every input scale — including the hot scale where a static
    threshold calibrated at scale 1 floods (the ROC sweep's headline)."""
    a = rng.standard_normal((128, 256)).astype(np.float32) * scale
    b = rng.standard_normal((128, 256)).astype(np.float32) * scale
    c = np.zeros((128, 128), np.float32)
    for strategy in ("rowcol", "weighted"):
        res = make_ft_sgemm(TILE, alpha=1.0, beta=0.0, strategy=strategy,
                            threshold="adaptive")(a, b, c)
        assert int(res.num_detected) == 0, (strategy, scale)
        assert int(res.num_uncorrectable) == 0, (strategy, scale)


@pytest.mark.parametrize("in_dtype", ["bfloat16", "float8_e4m3fn"])
def test_adaptive_low_precision_float_corrects(in_dtype):
    a, b, c = _inputs(128, 128, 256, seed=3)
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    ft = make_ft_sgemm(TILE, alpha=ALPHA, beta=BETA, strategy="rowcol",
                       threshold="adaptive", in_dtype=in_dtype)
    res = ft(a, b, c, inject=inj)
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA,
                                      in_dtype=in_dtype))
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok, f"{in_dtype}: {nbad} corrupted elements survived"
    assert int(res.num_detected) == 2
    assert int(res.num_uncorrectable) == 0


# -- 4. low-precision variants vs the dtype-matched oracle -------------------


def test_fp8_verifies_against_reference():
    """fp8 inputs, f32 accumulation/checksums: the corrected output equals
    the XLA oracle over the same fp8-rounded inputs within the reference
    tolerance (dtype-scaled by construction: both sides consume the
    rounded values, so only f32 accumulation noise remains)."""
    a, b, c = _inputs(256, 128, 512, seed=5)
    inj = InjectionSpec(enabled=True, every=2, magnitude=10000.0)
    for strategy in ("rowcol", "weighted"):
        ft = make_ft_sgemm(TILE, alpha=ALPHA, beta=BETA, strategy=strategy,
                           in_dtype="fp8_e4m3")  # alias spelling
        assert ft.in_dtype == jax.numpy.float8_e4m3fn
        res = ft(a, b, c, inj)
        want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA,
                                          in_dtype="float8_e4m3fn"))
        ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
        assert ok, f"fp8/{strategy}: {nbad} bad elements"
        assert int(res.num_detected) > 0
        assert int(res.num_uncorrectable) == 0


def test_int8_exact_accumulation_matches_oracle():
    """int8 inputs, int32 accumulation: clean residuals are identically
    zero (integer arithmetic), the output matches the exact int32 oracle,
    and injected integer faults are corrected exactly."""
    a, b, c = _int_inputs(256, 128, 512, seed=11)
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA, in_dtype="int8"))
    for strategy in ("rowcol", "global"):
        ft = make_ft_sgemm(TILE, alpha=ALPHA, beta=BETA, strategy=strategy,
                           in_dtype="int8", threshold="adaptive")
        clean = ft(a, b, c)
        assert int(clean.num_detected) == 0, strategy
        okc, nbadc, _ = verify_matrix(want, np.asarray(clean.c),
                                      verbose=False)
        assert okc, f"int8/{strategy} clean: {nbadc} bad"
        inj = InjectionSpec(enabled=True, every=1, magnitude=1.0)
        res = ft(a, b, c, inj)
        # 2 output tiles (m=256 over bm=128) x nk=4 unit faults each.
        assert int(res.num_detected) == 8, strategy
        if strategy == "rowcol":
            ok, nbad, _ = verify_matrix(want, np.asarray(res.c),
                                        verbose=False)
            assert ok, f"int8 unit faults survived: {nbad}"
            assert int(res.num_uncorrectable) == 0


def test_int8_static_threshold_works_too():
    a, b, c = _int_inputs(128, 128, 256, seed=2)
    ft = make_ft_sgemm(TILE, alpha=ALPHA, beta=BETA, strategy="rowcol",
                       in_dtype="int8", threshold=0.5)
    res = ft(a, b, c, InjectionSpec(enabled=True, every=1, magnitude=5.0))
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA, in_dtype="int8"))
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok and int(res.num_detected) == 2
    assert int(res.num_uncorrectable) == 0


def test_int8_rectangular_with_padding():
    a, b, c = _int_inputs(200, 150, 300, seed=13)
    ft = make_ft_sgemm(TILE, alpha=ALPHA, beta=BETA, strategy="rowcol",
                       in_dtype="int8", threshold="adaptive")
    res = ft(a, b, c, InjectionSpec(enabled=True, every=1, magnitude=3.0))
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA, in_dtype="int8"))
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok, f"int8/rect: {nbad} bad"
    assert int(res.num_detected) > 0
    assert int(res.num_uncorrectable) == 0


# -- 5. legality + vmem model ------------------------------------------------


def test_dtype_legality_errors():
    with pytest.raises(ValueError, match="int8"):
        make_ft_sgemm(TILE, strategy="weighted", in_dtype="int8")
    with pytest.raises(ValueError, match="1-byte"):
        make_ft_sgemm(TILE, strategy="rowcol", encode="mxu",
                      in_dtype="int8")
    with pytest.raises(ValueError, match="1-byte"):
        make_ft_sgemm(TILE, strategy="fused", in_dtype="float8_e4m3")
    with pytest.raises(ValueError, match="multifault"):
        make_ft_sgemm(TILE, strategy="rowcol", in_dtype="int8",
                      multifault=True)
    with pytest.raises(ValueError, match="in_dtype"):
        make_ft_sgemm(TILE, in_dtype="float64")
    # Aliases resolve; the canonical family is fixed.
    assert canonical_in_dtype("fp8") == "float8_e4m3fn"
    assert canonical_in_dtype("fp8_e4m3") == "float8_e4m3fn"
    assert set(IN_DTYPES) == {"float32", "bfloat16", "float8_e4m3fn",
                              "int8"}
    # Legal combos pass through and return the canonical name.
    assert check_kernel_legality(strategy="rowcol", encode="vpu",
                                 in_dtype="int8") == "int8"
    assert aug_rows(1) == 32  # 1-byte sublane granule


def test_vmem_model_covers_adaptive_and_exact():
    from ft_sgemm_tpu.ops.vmem import estimate_vmem_bytes

    base = estimate_vmem_bytes(TILE, "rowcol")
    adapt = estimate_vmem_bytes(TILE, "rowcol", adaptive=True)
    assert adapt == base + 16, "adaptive moment scratch must be modeled"
    exact = estimate_vmem_bytes(TILE, "rowcol", in_itemsize=1, exact=True)
    base1 = estimate_vmem_bytes(TILE, "rowcol", in_itemsize=1)
    assert exact == base1 + TILE.bm * TILE.bn * 4, (
        "int8 accumulator block must be modeled")


def test_tuner_space_threads_threshold_mode():
    from ft_sgemm_tpu.tuner.space import enumerate_space, variant_for

    assert variant_for("weighted", threshold_mode="adaptive") == "weighted"
    assert variant_for("weighted", threshold_mode="static") == (
        "weighted_precomp")
    feasible, _ = enumerate_space(128, 128, 128, strategy="rowcol",
                                  in_dtype="int8",
                                  threshold_mode="adaptive")
    assert feasible, "int8 adaptive space must be searchable"


# -- 6. tuner: thr= / dtype keys + schema migration --------------------------


def test_tuner_key_separates_threshold_modes_and_dtypes():
    from ft_sgemm_tpu import tuner

    kws = dict(strategy="rowcol", in_dtype="float32",
               injection_enabled=False)
    k_static = tuner.make_key(256, 256, 256, **kws)
    k_adapt = tuner.make_key(256, 256, 256, threshold_mode="adaptive",
                             **kws)
    assert "thr=static" in k_static and "thr=adaptive" in k_adapt
    assert k_static != k_adapt
    # auto shares static's program: same key.
    assert tuner.make_key(256, 256, 256, threshold_mode="auto",
                          **kws) == k_static
    # dtype axis: int8 and fp8 key distinctly, aliases normalize.
    k_int8 = tuner.make_key(256, 256, 256, strategy="rowcol",
                            in_dtype="int8", injection_enabled=False)
    k_fp8 = tuner.make_key(256, 256, 256, strategy="rowcol",
                           in_dtype="fp8_e4m3", injection_enabled=False)
    assert "|int8|" in k_int8 and "|float8_e4m3fn|" in k_fp8


def test_schema2_cache_misses_cleanly_after_bump(tmp_path, monkeypatch):
    """Satellite fix: a schema-2 cache file (pre-thr=/dtype-axis) must be
    ignored WITH A WARNING and treated as a miss — dispatch falls back to
    heuristics, a re-tune writes the CURRENT schema, and at no point does
    a stale key raise or mis-serve a tile. (The 3->4 migration pin lives
    in tests/test_variants.py; this one keeps the older generation
    covered too.)"""
    from ft_sgemm_tpu import tuner
    from ft_sgemm_tpu.tuner import cache as tcache

    path = tmp_path / "schema2.json"
    path.write_text(json.dumps(
        {"schema": 2, "entries": {
            "cpu|128x128x128|float32|rowcol|enc=vpu|inj=0": {
                "block": [128, 128, 128]}}}))
    monkeypatch.setenv(tcache.ENV_CACHE_PATH, str(path))
    tcache.clear_memo()
    try:
        with pytest.warns(UserWarning, match="schema"):
            assert tcache.load_entries() == {}
        # Dispatch lookup: a clean miss, never an exception.
        assert tuner.lookup_tile(128, 128, 128, strategy="rowcol",
                                 in_dtype="float32",
                                 injection_enabled=False) is None
        # Re-tune overwrites with a CURRENT-schema document and serves it.
        report = tuner.tune(128, strategy="rowcol", budget=1, reps=1,
                            samples=1, method="interpret")
        assert report["best"] is not None
        doc = json.loads(path.read_text())
        assert doc["schema"] == tcache.SCHEMA_VERSION >= 4
        tcache.clear_memo()
        assert tuner.lookup_tile(128, 128, 128, strategy="rowcol",
                                 in_dtype="float32",
                                 injection_enabled=False) is not None
    finally:
        tcache.clear_memo()


def test_tune_adaptive_int8_persists_and_dispatches(tmp_path, monkeypatch):
    from ft_sgemm_tpu import tuner
    from ft_sgemm_tpu.tuner import cache as tcache

    monkeypatch.setenv(tcache.ENV_CACHE_PATH,
                       str(tmp_path / "tuner_cache.json"))
    tcache.clear_memo()
    try:
        report = tuner.tune(128, strategy="rowcol", in_dtype="int8",
                            threshold_mode="adaptive", budget=1,
                            reps=1, samples=1, method="interpret")
        assert report["best"] is not None
        assert "thr=adaptive" in report["key"]
        assert "|int8|" in report["key"]
        tile = tuner.lookup_tile(128, 128, 128, strategy="rowcol",
                                 in_dtype="int8", injection_enabled=False,
                                 threshold_mode="adaptive")
        assert tile is not None
        # The static-mode key stays a miss: no cross-mode bleed.
        assert tuner.lookup_tile(128, 128, 128, strategy="rowcol",
                                 in_dtype="int8", injection_enabled=False,
                                 threshold_mode="static") is None
    finally:
        tcache.clear_memo()


def test_tune_rejects_illegal_combo():
    from ft_sgemm_tpu import tuner

    with pytest.raises(ValueError, match="1-byte"):
        tuner.tune(128, strategy="rowcol", encode="mxu", in_dtype="int8",
                   dry_run=True)


# -- 7. telemetry: threshold-mode labels + variance extras -------------------


def test_telemetry_threshold_mode_labels_and_variance(tmp_path):
    from ft_sgemm_tpu import telemetry

    telemetry.reset()
    telemetry.configure(tmp_path / "thr.jsonl")
    try:
        a, b, c = _inputs(128, 128, 256, seed=4)
        inj = InjectionSpec(enabled=True, every=1)
        for thr in ("static", "adaptive"):
            ft = make_ft_sgemm(TILE, alpha=ALPHA, beta=BETA,
                               strategy="rowcol", threshold=thr)
            ft(a, b, c, inject=inj)
        reg = telemetry.get_registry()
        assert reg.total("ft_calls", threshold_mode="static") == 1
        assert reg.total("ft_calls", threshold_mode="adaptive") == 1
        telemetry.disable()
        events = list(telemetry.read_events(tmp_path / "thr.jsonl"))
        modes = {e.extra["threshold_mode"] for e in events}
        assert modes == {"static", "adaptive"}
        adaptive_ev = [e for e in events
                       if e.extra["threshold_mode"] == "adaptive"][0]
        # Recorded threshold value + variance estimate (ISSUE 7).
        assert adaptive_ev.threshold is not None
        assert adaptive_ev.extra.get("variance") is not None
        assert adaptive_ev.extra["variance"] > 0
    finally:
        telemetry.reset()


# -- 8. ROC sweep: adaptive dominates static ---------------------------------


def test_roc_sweep_adaptive_dominates(rng):
    """The acceptance artifact, at unit-test size: one noisy-dtype combo
    swept over input scales. Adaptive: zero clean false positives, full
    detection. Static (calibrated at scale 1): misses the cold scale's
    faults AND floods on the hot scale's clean noise."""
    from ft_sgemm_tpu.injection import roc_sweep

    art = roc_sweep(dtypes=("bfloat16",), strategies=("rowcol",),
                    encodes=("vpu",))
    s = art["summary"]
    combo = s["combos"]["bfloat16|rowcol|vpu"]
    assert combo["dominates"] and combo["strict"]
    assert combo["adaptive"]["fp_rate"] == 0.0
    assert combo["adaptive"]["detection_rate"] == 1.0
    assert combo["static"]["detection_rate"] < 1.0  # cold-scale misses
    assert combo["static"]["fp_rate"] > 0.0         # hot-scale flood
    assert s["all_dominate"] and s["adaptive_false_positives"] == 0


def test_summarize_roc_verdict_logic():
    from ft_sgemm_tpu.injection import RocPoint, summarize_roc

    def pt(mode, clean, det, expected=4):
        return RocPoint(dtype="bfloat16", strategy="rowcol", encode="vpu",
                        mode=mode, scale=1.0, threshold=None, magnitude=1.0,
                        clean_detections=clean, checks=4,
                        expected_faults=expected, detected=det)

    # Tie: dominates weakly, not strictly.
    s = summarize_roc([pt("static", 0, 4), pt("adaptive", 0, 4)])
    combo = s["combos"]["bfloat16|rowcol|vpu"]
    assert combo["dominates"] and not combo["strict"]
    # Static floods: strict domination.
    s = summarize_roc([pt("static", 7, 4), pt("adaptive", 0, 4)])
    assert s["combos"]["bfloat16|rowcol|vpu"]["strict"]
    # Adaptive misses where static detects: dominated.
    s = summarize_roc([pt("static", 0, 4), pt("adaptive", 0, 2)])
    assert not s["combos"]["bfloat16|rowcol|vpu"]["dominates"]
    assert not s["all_dominate"]
    # Over-detection (noise) caps at the expected count.
    s = summarize_roc([pt("static", 0, 9), pt("adaptive", 0, 4)])
    assert s["combos"]["bfloat16|rowcol|vpu"]["static"][
        "detection_rate"] == 1.0


# -- 9. roofline: peaks picked by stage dtype --------------------------------


def test_roofline_peaks_by_dtype():
    from ft_sgemm_tpu.perf import roofline

    v5e = roofline.find_spec("TPU v5 lite")
    assert v5e.peak_for("int8") == pytest.approx(394e12)
    assert v5e.peak_for("bfloat16") == pytest.approx(197e12)
    # fp8 on a part with no native rate: the bf16 ceiling (documented in
    # the spec source string), via the alias spelling.
    assert v5e.peak_for("fp8_e4m3") == pytest.approx(197e12)
    v6e = roofline.find_spec("TPU v6e")
    assert v6e.peak_for("float8_e4m3fn") == pytest.approx(1836e12)
    assert v6e.peak_for("int8") == pytest.approx(1836e12)
    cpu = roofline.find_spec(None)
    assert cpu.peak_for("int8") is not None
    assert cpu.peak_for("not_a_dtype") is None
    # The summary row carries the dtype-matched ceiling.
    row = roofline.roofline_summary(
        flops=1e12, bytes_accessed=1e9, seconds=0.01,
        device_kind="TPU v5 lite", dtype="int8")
    assert row["peak_gflops"] == pytest.approx(394e3)
    assert row["pct_peak_compute"] == pytest.approx(
        (1e12 / 0.01) / 394e12)


def test_int8_dispatch_respects_tuned_tile(tmp_path, monkeypatch):
    """End-to-end: a persisted int8-adaptive winner overrides the named-
    shape heuristic tile on the next dispatch (the cache key round-trip
    across the two new axes)."""
    from ft_sgemm_tpu import tuner
    from ft_sgemm_tpu.tuner import cache as tcache

    monkeypatch.setenv(tcache.ENV_CACHE_PATH, str(tmp_path / "c.json"))
    tcache.clear_memo()
    try:
        key = tuner.make_key(128, 128, 128, strategy="rowcol",
                             in_dtype="int8", injection_enabled=False,
                             threshold_mode="adaptive")
        tcache.store(key, {"block": [128, 128, 128]})
        a, b, c = _int_inputs(128, 128, 128, seed=1)
        ft = make_ft_sgemm("small", strategy="rowcol", in_dtype="int8",
                           threshold="adaptive")
        res = ft(a, b, c)
        assert int(res.num_detected) == 0
        stats = tuner.lookup_stats()
        assert stats["hits"] >= 1
    finally:
        tcache.clear_memo()
