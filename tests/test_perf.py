"""Performance-observability subsystem (``ft_sgemm_tpu.perf``).

Covers the four modules plus their wiring:

- roofline math on synthetic specs (arithmetic intensity, %-of-peak,
  bound verdicts, ABFT-overhead fractions from the cost breakdown);
- RunReport JSON round-trip and markdown rendering;
- compare verdicts (improve / regress / within-noise / incomparable) and
  the CLI exit-code contract (0 identical, nonzero on an injected >=20%
  slowdown, 0-with-incomparable on a missing stage — the acceptance
  criteria of the perf-observability PR);
- HLO introspection smoke on CPU with graceful degradation when
  ``cost_analysis``/``memory_analysis`` are unavailable;
- telemetry additions riding along: histogram percentiles from bucket
  counts and the Prometheus text export.
"""

import copy
import json
import math

import pytest

from ft_sgemm_tpu.perf import compare as perf_compare
from ft_sgemm_tpu.perf import report as perf_report
from ft_sgemm_tpu.perf import roofline


# ---------------------------------------------------------------------------
# roofline
# ---------------------------------------------------------------------------


SYNTH = roofline.DeviceSpec(
    name="synth", peak_flops={"float32": 1e12}, hbm_bytes_per_s=1e11,
    source="test")  # ridge point: 10 flops/byte


def test_roofline_summary_math_on_synthetic_spec():
    # 1e10 flops over 1e9 bytes in 0.1 s: 100 GFLOP/s = 10% of the 1 TF
    # peak; 10 GB/s = 10% of bandwidth; AI 10 = exactly at the ridge
    # (>= ridge counts as compute-bound).
    row = roofline.roofline_summary(
        flops=1e10, bytes_accessed=1e9, seconds=0.1, spec=SYNTH,
        dtype="float32", name="stage")
    assert row["gflops"] == pytest.approx(100.0)
    assert row["arithmetic_intensity"] == pytest.approx(10.0)
    assert row["pct_peak_compute"] == pytest.approx(0.10)
    assert row["pct_peak_bandwidth"] == pytest.approx(0.10)
    assert row["ridge_point"] == pytest.approx(10.0)
    assert row["bound"] == "compute"
    assert row["name"] == "stage"


def test_roofline_bound_verdict_flips_below_ridge():
    row = roofline.roofline_summary(
        flops=1e9, bytes_accessed=1e9, seconds=0.1, spec=SYNTH,
        dtype="float32")
    assert row["arithmetic_intensity"] == pytest.approx(1.0)
    assert row["bound"] == "memory"


def test_roofline_null_seconds_yields_null_rates_not_crash():
    for sec in (None, 0.0, -1.0):
        row = roofline.roofline_summary(
            flops=1e9, bytes_accessed=1e9, seconds=sec, spec=SYNTH)
        assert row["seconds"] is None
        assert row["gflops"] is None
        assert row["pct_peak_compute"] is None
        # The static facts still render.
        assert row["arithmetic_intensity"] == pytest.approx(1.0)


def test_find_spec_matches_tpu_kinds_and_falls_back():
    assert roofline.find_spec("TPU v4").name == "TPU v4"
    assert roofline.find_spec("TPU v5 lite").name == "TPU v5e"
    assert roofline.find_spec("TPU v5p").name == "TPU v5p"
    assert roofline.find_spec("TPU v6 lite").name == "TPU v6e"
    cpu = roofline.find_spec("some unknown accelerator")
    assert cpu.name == "cpu" and cpu.estimated
    assert roofline.find_spec(None).name == "cpu"
    # f32 peaks derive from bf16 via the 6-pass decomposition.
    v5e = roofline.find_spec("TPU v5e")
    assert v5e.peak_for("float32") == pytest.approx(
        v5e.peak_for("bfloat16") / roofline.F32_DERATE)


def test_abft_fractions_from_cost_breakdown():
    from ft_sgemm_tpu.ops.common import gemm_cost_breakdown

    m = n = k = 4096
    block = (512, 1024, 512)
    plain = gemm_cost_breakdown(m, n, k, 4)
    assert plain["flops_encode"] == plain["flops_check"] == 0
    assert roofline.abft_fractions(plain)["abft_fraction"] == 0.0

    ft = gemm_cost_breakdown(m, n, k, 4, block=block, strategy="rowcol",
                             check_every=2)
    fr = roofline.abft_fractions(ft)
    assert 0.0 < fr["encode_fraction"] < 0.5
    assert 0.0 < fr["check_fraction"] < 0.5
    assert fr["abft_fraction"] == pytest.approx(
        fr["encode_fraction"] + fr["check_fraction"])
    # The breakdown sums to exactly what gemm_cost_estimate reports.
    from ft_sgemm_tpu.ops.common import gemm_cost_estimate

    est = gemm_cost_estimate(m, n, k, 4, block=block, strategy="rowcol",
                             check_every=2)
    assert est.flops == (ft["flops_base"] + ft["flops_encode"]
                         + ft["flops_check"])
    assert est.bytes_accessed == (ft["bytes_base"] + ft["bytes_encode"]
                                  + ft["bytes_check"])


def test_stage_row_resolves_kernel_strategy_for_mxu_encode():
    # weighted+mxu runs the fused body: its row must carry MXU-encode
    # cost terms, not the precomp body's.
    r_vpu = perf_report.stage_row(
        "s", 0.01, m=4096, n=4096, k=4096, block=(512, 1024, 512),
        strategy="weighted", encode="vpu", device_kind="TPU v4")
    r_mxu = perf_report.stage_row(
        "s", 0.01, m=4096, n=4096, k=4096, block=(512, 1024, 512),
        strategy="weighted", encode="mxu", device_kind="TPU v4")
    assert r_mxu["flops"] > r_vpu["flops"]
    assert r_mxu["abft_fraction"] > 0
    assert r_vpu["strategy"] == "weighted" and r_vpu["encode"] == "vpu"
    assert not r_vpu["spec_estimated"]


# ---------------------------------------------------------------------------
# RunReport
# ---------------------------------------------------------------------------


def _report():
    rows = [perf_report.stage_row(
        "ft_rowcol", 0.0123, m=256, n=256, k=256, block=(128, 128, 128),
        strategy="rowcol", encode="vpu", device_kind="cpu")]
    manifest = perf_report.build_manifest(device_kind="cpu",
                                          probe_jax=False,
                                          extra={"note": "test"})
    return perf_report.RunReport(manifest=manifest, stages=rows)


def test_run_report_json_round_trip():
    rr = _report()
    back = perf_report.RunReport.from_json(rr.to_json())
    assert back.to_dict() == rr.to_dict()
    assert back.manifest["note"] == "test"
    assert back.stages[0]["name"] == "ft_rowcol"
    # And through an embedding artifact.
    artifact = {"metric": "x", "value": 1,
                "context": {"run_report": rr.to_dict()}}
    got = perf_report.from_artifact(artifact)
    assert got is not None and got.to_dict() == rr.to_dict()
    assert perf_report.from_artifact({"context": {}}) is None
    assert perf_report.from_artifact({}) is None


def test_run_report_markdown_renders_roofline_columns():
    md = _report().to_markdown()
    assert "| stage |" in md and "ft_rowcol" in md
    assert "ABFT" in md and "% peak compute" in md
    assert "device_kind" in md
    # Estimated CPU spec percentages are tilde-annotated.
    assert "~" in md


def test_run_report_timeline_round_trip_and_markdown():
    """The optional ``timeline`` section (streamed-span summary from
    telemetry.timeline) must survive JSON round-trips and render — with
    the kill point — in the markdown report."""
    rr = _report()
    rr.timeline = {
        "spans": [{"kind": "stage", "name": "ft_rowcol", "start": 0.0,
                   "end": 1.2, "seconds": 1.2, "status": "ok",
                   "value": 100.0, "error": None}],
        "in_flight": [{"kind": "stage", "name": "ft_fused", "start": 1.2}],
        "killed_at_stage": "ft_fused", "kills": [],
        "heartbeats": 3, "max_heartbeat_gap": 10.0,
        "t0": 0.0, "t1": 2.0, "wall_seconds": 2.0}
    back = perf_report.RunReport.from_json(rr.to_json())
    assert back.timeline == rr.timeline
    md = back.to_markdown()
    assert "## Timeline" in md
    assert "killed during" in md and "ft_fused" in md
    assert "in flight" in md
    assert "heartbeats" in md
    # Reports without a timeline render no empty section.
    assert "## Timeline" not in _report().to_markdown()


def test_build_manifest_survives_jax_free_process():
    m = perf_report.build_manifest(probe_jax=False)
    assert m["schema"] == perf_report.SCHEMA_VERSION
    assert m["jax_version"] is None
    assert m["python_version"]
    # tuner/fault-counter facts are present (possibly zero), not crashes.
    assert "tuner_cache" in m and "fault_counters" in m


# ---------------------------------------------------------------------------
# compare
# ---------------------------------------------------------------------------


def _artifact(headline=30000.0, xla=32000.0, stage_sec=0.01):
    return {
        "metric": "abft_kernel_huge_gflops_4096",
        "value": headline,
        "context": {
            "xla_dot_gflops": xla,
            "run_report": {"manifest": {}, "stages": [
                {"name": "ft_rowcol", "seconds": stage_sec},
            ]},
        },
    }


def test_compare_identical_artifacts_exit_0():
    a = _artifact()
    res = perf_compare.compare(a, copy.deepcopy(a))
    assert perf_compare.exit_code(res) == 0
    assert res["counts"]["regression"] == 0
    assert res["counts"]["incomparable"] == 0
    assert res["counts"]["within_noise"] == len(res["stages"]) > 0


def test_compare_20pct_slowdown_regresses_exit_1():
    a = _artifact()
    b = _artifact(headline=30000.0 * 0.8,  # -20% GFLOPS
                  stage_sec=0.01 * 1.25)   # +25% seconds
    res = perf_compare.compare(a, b)
    assert perf_compare.exit_code(res) == 1
    assert "abft_kernel_huge_gflops_4096" in res["regressions"]
    assert "stage[ft_rowcol].seconds" in res["regressions"]
    # The unchanged stage stays within noise.
    by_name = {r["stage"]: r for r in res["stages"]}
    assert by_name["xla_dot_gflops"]["verdict"] == "within_noise"


def test_compare_improvement_and_direction_of_seconds():
    a = _artifact()
    b = _artifact(headline=30000.0 * 1.3, stage_sec=0.01 / 1.3)
    res = perf_compare.compare(a, b)
    assert perf_compare.exit_code(res) == 0
    by_name = {r["stage"]: r for r in res["stages"]}
    assert by_name["abft_kernel_huge_gflops_4096"]["verdict"] == \
        "improvement"
    # Faster seconds is an improvement with a POSITIVE goodness delta.
    row = by_name["stage[ft_rowcol].seconds"]
    assert row["verdict"] == "improvement" and row["delta"] > 0


def test_compare_missing_and_null_stages_incomparable_exit_0():
    a = _artifact()
    b = _artifact()
    del b["context"]["xla_dot_gflops"]
    b["context"]["run_report"]["stages"][0]["seconds"] = None
    b["value"] = None  # a null headline (the r01..r05 artifact shape)
    res = perf_compare.compare(a, b)
    assert perf_compare.exit_code(res) == 0
    assert res["counts"]["incomparable"] == 3
    assert res["counts"]["regression"] == 0
    reasons = {r["stage"]: r.get("reason") for r in res["stages"]
               if r["verdict"] == "incomparable"}
    assert all("missing in candidate" in v for v in reasons.values())
    # And the rendering names them without crashing.
    text = perf_compare.format_comparison(res)
    assert "incomparable" in text


def test_compare_tolerance_band_is_respected():
    a = _artifact()
    b = _artifact(headline=30000.0 * 0.7)
    loose = perf_compare.compare(a, b, tolerance=0.5)
    tight = perf_compare.compare(a, b, tolerance=0.1)
    assert perf_compare.exit_code(loose) == 0
    assert perf_compare.exit_code(tight) == 1


def test_compare_smoke_artifacts_and_zero_baseline():
    smoke = {"metric": "bench_smoke", "value": 1,
             "context": {"encode_modes": {
                 "vpu": {"seconds": 0.5}, "mxu": {"seconds": 0.4}}}}
    res = perf_compare.compare(smoke, copy.deepcopy(smoke))
    names = {r["stage"] for r in res["stages"]}
    # The 0/1 smoke ok flag is not a measurement; the seconds are.
    assert names == {"smoke_encode[vpu].seconds",
                     "smoke_encode[mxu].seconds"}
    z = {"metric": "m", "value": 0.0, "context": {}}
    res = perf_compare.compare(z, z)
    assert all(r["verdict"] == "incomparable" for r in res["stages"])


def test_load_artifact_last_json_line_and_driver_wrapper(tmp_path):
    p = tmp_path / "a.json"
    p.write_text("some log line\n"
                 '{"metric": "m", "value": 1.0, "context": {}}\n')
    assert perf_compare.load_artifact(str(p))["value"] == 1.0
    w = tmp_path / "wrapped.json"
    w.write_text(json.dumps(
        {"rc": 0, "parsed": {"metric": "m", "value": 2.0}}))
    assert perf_compare.load_artifact(str(w))["value"] == 2.0
    bad = tmp_path / "bad.json"
    bad.write_text("no json here\n")
    with pytest.raises(ValueError):
        perf_compare.load_artifact(str(bad))


def test_cli_bench_compare_exit_codes(tmp_path, capsys):
    from ft_sgemm_tpu.cli import main as cli_main

    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps(_artifact()))
    b.write_text(json.dumps(_artifact()))
    assert cli_main(["cli", "bench-compare", str(a), str(b)]) == 0
    slow = _artifact(headline=30000.0 * 0.75)
    b.write_text(json.dumps(slow))
    assert cli_main(["cli", "bench-compare", str(a), str(b)]) == 1
    # Loose tolerance turns the same delta into noise.
    assert cli_main(["cli", "bench-compare", str(a), str(b),
                     "--tolerance=0.5"]) == 0
    assert cli_main(["cli", "bench-compare", str(a),
                     str(tmp_path / "absent.json")]) == 2
    capsys.readouterr()


def test_cli_report_renders_and_flags_reportless_artifacts(tmp_path,
                                                           capsys):
    from ft_sgemm_tpu.cli import main as cli_main

    art = tmp_path / "art.json"
    art.write_text(json.dumps(
        {"metric": "m", "value": 1.0,
         "context": {"run_report": _report().to_dict()}}))
    assert cli_main(["cli", "report", str(art)]) == 0
    out = capsys.readouterr().out
    assert "## Roofline" in out and "ft_rowcol" in out
    assert cli_main(["cli", "report", str(art), "--format=json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["stages"][0]["name"] == "ft_rowcol"
    # A null artifact has no report: exit 1, not a crash.
    art.write_text(json.dumps({"metric": "m", "value": None,
                               "context": {}}))
    assert cli_main(["cli", "report", str(art)]) == 1
    assert cli_main(["cli", "report",
                     str(tmp_path / "absent.json")]) == 2
    capsys.readouterr()


# ---------------------------------------------------------------------------
# HLO introspection
# ---------------------------------------------------------------------------


def test_hlo_introspection_smoke_on_cpu():
    import jax.numpy as jnp

    from ft_sgemm_tpu.perf import hlo as perf_hlo

    def f(a, b):
        return jnp.dot(a, b) + 1.0

    a = jnp.ones((128, 128), jnp.float32)
    out = perf_hlo.introspect_jitted(f, a, a, label="dot_smoke")
    assert out["label"] == "dot_smoke"
    assert out["lower_seconds"] > 0
    assert out["compile_seconds"] > 0
    assert out["hlo_counts"]["dot_general"] >= 1
    # json-serializable end to end (it rides the bench artifact).
    json.dumps(out)


def test_hlo_introspection_degrades_when_analyses_unavailable(
        monkeypatch):
    """A backend whose compiled artifact refuses cost/memory analysis
    must degrade to named 'unavailable' reasons, not an exception."""
    import jax
    import jax.numpy as jnp

    from ft_sgemm_tpu.perf import hlo as perf_hlo

    class Hostile:
        def cost_analysis(self):
            raise NotImplementedError("no cost model on this backend")

        def memory_analysis(self):
            raise RuntimeError("tunnel closed")

        def as_text(self):
            raise RuntimeError("no HLO text either")

    class Lowered:
        def compile(self):
            return Hostile()

    class Jitted:
        def lower(self, *args):
            return Lowered()

    out = perf_hlo.introspect_jitted(Jitted(), label="hostile")
    assert out["cost_analysis"] is None
    assert out["memory_analysis"] is None
    assert out["hlo_counts"] is None
    assert "NotImplementedError" in out["unavailable"]["cost_analysis"]
    assert "RuntimeError" in out["unavailable"]["memory_analysis"]
    assert "hlo_text" in out["unavailable"]

    # A lower()-time failure (backend init dead) is also a record.
    class DeadJitted:
        def lower(self, *args):
            raise RuntimeError("Unable to initialize backend")

    out = perf_hlo.introspect_jitted(DeadJitted(), label="dead")
    assert out["compile_seconds"] is None
    assert "lower" in out["unavailable"]

    # Sanity: the real path still records into a registry when asked.
    from ft_sgemm_tpu.telemetry import MetricsRegistry

    reg = MetricsRegistry()
    perf_hlo.introspect_jitted(
        lambda a: jnp.sum(a * 2), jnp.ones((8,)), label="tiny",
        registry=reg)
    names = {s["name"] for s in reg.collect()}
    assert "compile.compile_seconds" in names
    assert any(n.startswith("hlo.") for n in names)


def test_hlo_cost_normalization_shapes():
    from ft_sgemm_tpu.perf.hlo import _normalize_cost, hlo_op_counts

    assert _normalize_cost(None) is None
    assert _normalize_cost([]) is None
    assert _normalize_cost({"flops": 10.0, "weird": object()}) == \
        {"flops": 10.0}
    assert _normalize_cost([{"flops": 3}])["flops"] == 3.0
    text = ("%f = f32[8]{0} fusion(%p), kind=kLoop\n"
            "%d = f32[8,8]{1,0} dot(%a, %b), lhs_contracting_dims={1}\n"
            "%c = (f32[1]) custom-call(%d), custom_call_target=\"x\"\n")
    counts = hlo_op_counts(text)
    assert counts["dot_general"] == 1
    assert counts["fusion"] == 1
    assert counts["custom_call"] == 1


# ---------------------------------------------------------------------------
# tuner lookup stats (manifest input)
# ---------------------------------------------------------------------------


def test_tuner_lookup_stats_count_hits_and_misses(tmp_path, monkeypatch):
    from ft_sgemm_tpu import tuner

    monkeypatch.setenv(tuner.ENV_CACHE_PATH,
                       str(tmp_path / "cache.json"))
    tuner.cache.clear_memo()
    tuner.reset_lookup_stats()
    assert tuner.lookup_stats() == {"hits": 0, "misses": 0}
    assert tuner.lookup_tile(256, 256, 256, strategy="weighted",
                             in_dtype="float32",
                             injection_enabled=False) is None
    assert tuner.lookup_stats() == {"hits": 0, "misses": 1}
    key = tuner.make_key(256, 256, 256, strategy="weighted",
                         in_dtype="float32", injection_enabled=False)
    tuner.cache.store(key, {"block": [128, 128, 128]})
    assert tuner.lookup_tile(256, 256, 256, strategy="weighted",
                             in_dtype="float32",
                             injection_enabled=False) is not None
    assert tuner.lookup_stats() == {"hits": 1, "misses": 1}
    # Disabled lookups ask nothing of the cache and count nothing.
    with tuner.override_disabled():
        assert tuner.lookup_tile(256, 256, 256, strategy="weighted",
                                 in_dtype="float32",
                                 injection_enabled=False) is None
    assert tuner.lookup_stats() == {"hits": 1, "misses": 1}
    tuner.reset_lookup_stats()


# ---------------------------------------------------------------------------
# telemetry additions: percentiles + Prometheus export
# ---------------------------------------------------------------------------


def test_histogram_percentiles_from_bucket_counts():
    from ft_sgemm_tpu.telemetry import Histogram, histogram_percentiles

    h = Histogram("h", (), buckets=(1.0, 10.0, 100.0, float("inf")))
    for v in [0.5] * 50 + [5.0] * 45 + [50.0] * 4 + [1e9]:
        h.observe(v)
    pct = histogram_percentiles(h.value)
    assert pct["p50"] == 1.0      # 50th obs sits in the first bucket
    assert pct["p95"] == 10.0
    assert math.isinf(pct["max"])  # the 1e9 landed in the overflow bucket

    empty = Histogram("e", ())
    pct = histogram_percentiles(empty.value)
    assert pct == {"p50": None, "p95": None, "max": None}


def test_prometheus_export_format():
    from ft_sgemm_tpu.telemetry import MetricsRegistry, to_prometheus

    reg = MetricsRegistry()
    reg.counter("ft_detections", op="ft_sgemm", strategy="weighted").inc(4)
    reg.gauge("compile.seconds", stage="xla_dot").set(1.5)
    reg.histogram("ft_residual", buckets=(1.0, float("inf")),
                  op="ft_sgemm").observe(0.5)
    text = to_prometheus(reg.collect())
    assert "# TYPE ft_detections counter" in text
    assert ('ft_detections{op="ft_sgemm",strategy="weighted"} 4'
            in text)
    # Dots sanitize to underscores; gauges are typed.
    assert "# TYPE compile_seconds gauge" in text
    assert 'compile_seconds{stage="xla_dot"} 1.5' in text
    # Histograms: cumulative buckets + +Inf + sum/count.
    assert 'ft_residual_bucket{le="1.0",op="ft_sgemm"} 1' in text
    assert 'ft_residual_bucket{le="+Inf",op="ft_sgemm"} 1' in text
    assert 'ft_residual_sum{op="ft_sgemm"} 0.5' in text
    assert 'ft_residual_count{op="ft_sgemm"} 1' in text
    assert to_prometheus([]) == ""


def test_cli_telemetry_prom_export(tmp_path, capsys):
    from ft_sgemm_tpu.cli import main as cli_main

    log = tmp_path / "events.jsonl"
    log.write_text(json.dumps(
        {"outcome": "corrected", "op": "ft_sgemm", "detected": 2,
         "corrected": 2, "uncorrectable": 0, "strategy": "weighted",
         "residual": 9500.0}) + "\n")
    assert cli_main(["cli", "telemetry", str(log), "--format=prom"]) == 0
    out = capsys.readouterr().out
    assert "# TYPE ft_calls counter" in out
    assert 'ft_detections{op="ft_sgemm",strategy="weighted"} 2' in out
    assert "ft_residual_bucket" in out
    # The text summary now carries percentile estimates.
    assert cli_main(["cli", "telemetry", str(log)]) == 0
    out = capsys.readouterr().out
    assert "residual percentiles" in out and "p50<=" in out


# ---------------------------------------------------------------------------
# bench artifact integration (no subprocess: the emit-side wiring)
# ---------------------------------------------------------------------------


def test_bench_emit_surfaces_fallback_smoke_and_run_report(capsys):
    import importlib.util
    import pathlib

    bench_path = (pathlib.Path(__file__).resolve().parent.parent
                  / "bench.py")
    spec = importlib.util.spec_from_file_location("bench_emit_test",
                                                  bench_path)
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    bench._PRE_VALUES = {}
    rr = {"manifest": {"device_kind": "cpu"}, "stages": []}
    rc = bench._emit(
        {"backend": {"backend": "cpu", "platform_requested": "tpu",
                     "platform_used": "cpu", "fallback_reason": "boom"},
         "fallback_smoke": {"ok": True, "encode_modes": {},
                            "run_report": rr}},
        {})
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    # No headline, but the fallback measured: rc 0 and the artifact
    # carries the platform triple + the hoisted RunReport.
    assert rc == 0
    assert payload["value"] is None
    ctx = payload["context"]
    assert ctx["platform_requested"] == "tpu"
    assert ctx["platform_used"] == "cpu"
    assert ctx["fallback_reason"] == "boom"
    assert ctx["run_report"] == rr
    assert ctx["fallback_smoke"]["ok"] is True
    assert "run_report" not in ctx["fallback_smoke"]
