"""Native host-runtime tests (csrc/hostutils.cpp via ctypes)."""

import numpy as np
import pytest

from ft_sgemm_tpu import runtime

pytestmark = pytest.mark.skipif(
    not runtime.available(), reason="no native toolchain (numpy fallback ok)"
)


def test_native_matrix_quantized_and_deterministic():
    a1 = runtime.generate_random_matrix_native(32, 48, seed=10)
    a2 = runtime.generate_random_matrix_native(32, 48, seed=10)
    np.testing.assert_array_equal(a1, a2)
    assert a1.shape == (32, 48) and a1.dtype == np.float32
    scaled = np.abs(a1) * 10
    np.testing.assert_allclose(scaled, np.round(scaled), atol=1e-5)
    assert scaled.max() <= 9
    b = runtime.generate_random_matrix_native(32, 48, seed=11)
    assert not np.array_equal(a1, b)


def test_driver_inputs_continue_one_stream():
    # A then B from one srand(10) stream (sgemm.cu:12,57-58): B must differ
    # from a fresh seed-10 A, and the pair must be reproducible.
    a1, b1 = runtime.generate_reference_driver_inputs(16)
    a2, b2 = runtime.generate_reference_driver_inputs(16)
    np.testing.assert_array_equal(a1, a2)
    np.testing.assert_array_equal(b1, b2)
    assert not np.array_equal(a1, b1)


def test_native_verify_matrix_matches_python():
    from ft_sgemm_tpu.utils.matrices import verify_matrix

    rng = np.random.default_rng(0)
    ref = rng.normal(size=(64, 64)).astype(np.float32)
    out = ref.copy()
    out[5, 7] += 1.0
    out[20, 3] += 0.005  # abs below tolerance -> passes
    ok_n, nbad_n, first_n = runtime.verify_matrix_native(ref, out)
    ok_p, nbad_p, first_p = verify_matrix(ref, out, verbose=False)
    assert ok_n == ok_p is False
    assert nbad_n == nbad_p == 1
    assert first_n == 5 * 64 + 7
    assert first_p == (5, 7)


def test_checksum_residual_native_oracle():
    rng = np.random.default_rng(2)
    c = rng.normal(size=(16, 24)).astype(np.float32)
    er = c.astype(np.float64).sum(axis=1)
    ec = c.astype(np.float64).sum(axis=0)
    r, cl = runtime.checksum_residual_native(c, er, ec)
    assert r < 1e-3 and cl < 1e-3
    # Corrupt one element: both residuals see ~the fault magnitude.
    c2 = c.copy()
    c2[3, 5] += 100.0
    r, cl = runtime.checksum_residual_native(c2, er, ec)
    assert abs(r - 100.0) < 1e-2 and abs(cl - 100.0) < 1e-2


def test_native_cpu_gemm_matches_numpy():
    rng = np.random.default_rng(1)
    a = rng.normal(size=(17, 23)).astype(np.float32)
    b = rng.normal(size=(23, 11)).astype(np.float32)
    c = rng.normal(size=(17, 11)).astype(np.float32)
    got = runtime.cpu_gemm_native(1.25, -0.5, a, b, c)
    want = 1.25 * (a.astype(np.float64) @ b.astype(np.float64)) - 0.5 * c
    np.testing.assert_allclose(got, want.astype(np.float32), rtol=1e-5, atol=1e-5)
