"""Fault-telemetry subsystem: registry, event log, zero-cost-off.

Pins the three contract points of ``ft_sgemm_tpu.telemetry``:

1. the metrics registry aggregates correctly across label sets and is a
   strict no-op when telemetry is disabled;
2. a jitted clean run's HLO is BYTE-IDENTICAL with telemetry on, off, or
   never configured (recording is host-side observation, never traced
   computation);
3. the JSONL event log round-trips through the CLI summarizer, and its
   aggregated counters exactly match the summed ``FtSgemmResult``
   counters of the run that produced it (the acceptance criterion).
"""

import threading

import jax
import numpy as np
import pytest

from ft_sgemm_tpu import InjectionSpec, ft_sgemm, make_ft_sgemm, telemetry
from ft_sgemm_tpu.configs import KernelShape
from ft_sgemm_tpu.telemetry import (
    FaultEvent,
    JsonlSink,
    MetricsRegistry,
    format_summary,
    read_events,
    summarize_events,
)

TILE = KernelShape("t128", 128, 128, 128, (0,) * 7)


@pytest.fixture(autouse=True)
def _clean_telemetry():
    """Every test starts and ends with telemetry fully reset — the
    subsystem is process-global state."""
    telemetry.reset()
    yield
    telemetry.reset()


def _inputs(rng, m=128, n=128, k=256):
    return (rng.standard_normal((m, k)).astype(np.float32),
            rng.standard_normal((n, k)).astype(np.float32),
            rng.standard_normal((m, n)).astype(np.float32))


# -- registry ---------------------------------------------------------------


def test_registry_label_aggregation():
    reg = MetricsRegistry()
    reg.counter("ft_detections", op="gemm", strategy="weighted").inc(3)
    reg.counter("ft_detections", op="gemm", strategy="rowcol").inc(2)
    reg.counter("ft_detections", op="attn", strategy="weighted").inc(5)
    reg.counter("other", op="gemm").inc(100)
    assert reg.total("ft_detections") == 10
    assert reg.total("ft_detections", op="gemm") == 5
    assert reg.total("ft_detections", strategy="weighted") == 8
    assert reg.total("ft_detections", op="nope") == 0
    # Same name+labels returns the same series object (hot paths may
    # cache the handle).
    c1 = reg.counter("ft_detections", op="gemm", strategy="weighted")
    c2 = reg.counter("ft_detections", strategy="weighted", op="gemm")
    assert c1 is c2


def test_registry_gauge_and_histogram():
    reg = MetricsRegistry()
    g = reg.gauge("vmem_bytes", op="gemm")
    g.set(3.5)
    g.set(7.25)
    assert g.value == 7.25
    h = reg.histogram("ft_residual", buckets=(1.0, 10.0), op="gemm")
    for v in (0.5, 5.0, 5.0, 1e9):
        h.observe(v)
    snap = h.value
    assert snap["buckets"] == [1.0, 10.0, float("inf")]
    assert snap["counts"] == [1, 2, 1]
    assert snap["count"] == 4
    # collect() snapshots every series with its labels.
    kinds = {(s["kind"], s["name"]) for s in reg.collect()}
    assert ("gauge", "vmem_bytes") in kinds
    assert ("histogram", "ft_residual") in kinds


def test_registry_thread_safety():
    reg = MetricsRegistry()

    def work():
        for _ in range(1000):
            reg.counter("n", op="x").inc()

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert reg.total("n") == 8000


def test_disabled_recording_is_noop(rng):
    a, b, c = _inputs(rng)
    res = ft_sgemm(a, b, c, TILE, inject=InjectionSpec(enabled=True))
    assert not telemetry.enabled()
    assert telemetry.record_gemm("op", res) is None
    assert telemetry.record_step_event("retry") is None
    assert telemetry.get_registry().collect() == []


def test_tracer_results_are_skipped(rng):
    """Recording inside a caller's jit must observe nothing (tracers) and
    must not crash the trace."""
    a, b, c = _inputs(rng)
    telemetry.configure(None)
    ft = make_ft_sgemm(TILE)

    @jax.jit
    def f(a, b, c):
        return ft(a, b, c, InjectionSpec(enabled=True)).c

    np.asarray(f(a, b, c))
    reg = telemetry.get_registry()
    # The traced call was skipped: no counters from inside the jit.
    assert reg.total("ft_detections") == 0


# -- zero-cost off: jitted HLO is identical on/off --------------------------


def test_jitted_hlo_identical_with_telemetry_on_off(rng, tmp_path):
    a, b, c = _inputs(rng)
    ft = make_ft_sgemm(TILE)

    def lower_text():
        return jax.jit(lambda a, b, c: ft(a, b, c).c).lower(a, b, c
                                                            ).as_text()

    baseline = lower_text()
    telemetry.configure(tmp_path / "t.jsonl", measure_residual=True,
                        log_clean=True)
    enabled = lower_text()
    telemetry.disable()
    disabled = lower_text()
    assert enabled == baseline, "telemetry ON changed the jitted HLO"
    assert disabled == baseline, "telemetry OFF changed the jitted HLO"


# -- event log + acceptance: counters match the summed results --------------


def test_event_counts_match_ft_results_exactly(rng, tmp_path):
    log = tmp_path / "faults.jsonl"
    telemetry.configure(log, measure_residual=True, log_clean=True)
    specs = [InjectionSpec(enabled=True, every=1),
             InjectionSpec(enabled=True, every=2),
             InjectionSpec(enabled=True, every=1, col_stride=0),  # adversarial
             InjectionSpec.none()]
    want_det = want_unc = 0
    for spec in specs:
        a, b, c = _inputs(rng)
        res = ft_sgemm(a, b, c, TILE, inject=spec)
        want_det += int(res.num_detected)
        want_unc += int(res.num_uncorrectable)
    telemetry.disable()

    events = list(read_events(log))
    assert len(events) == len(specs)  # log_clean: the clean call too
    summary = summarize_events(events)
    assert summary["totals"]["detected"] == want_det
    assert summary["totals"]["uncorrectable"] == want_unc
    assert summary["totals"]["corrected"] == want_det
    # The adversarial same-column schedule must have produced at least
    # one uncorrectable event (otherwise this test pins nothing).
    assert want_unc > 0
    assert summary["outcomes"].get("uncorrectable", 0) >= 1
    # Registry aggregates agree with the event log.
    reg = telemetry.get_registry()
    assert reg.total("ft_detections") == want_det
    assert reg.total("ft_uncorrectable") == want_unc
    # measure_residual mode: every event carries a residual observation
    # and the histogram saw all of them.
    assert all(e.residual is not None for e in events)
    assert summary["residuals"]["count"] == len(events)


def test_events_carry_tile_coordinates_and_threshold(rng, tmp_path):
    log = tmp_path / "faults.jsonl"
    telemetry.configure(log)
    a, b, c = _inputs(rng, m=256, n=128)  # 2x1 tile grid
    res = ft_sgemm(a, b, c, TILE, inject=InjectionSpec(enabled=True))
    telemetry.disable()
    (ev,) = list(read_events(log))
    assert ev.outcome == "corrected"
    assert ev.threshold == pytest.approx(9500.0)
    det = np.asarray(res.detections)
    assert ev.tiles == [[int(i), int(j)] for i, j in np.argwhere(det != 0)]
    assert ev.strategy == "weighted"


def test_attention_events_record_softmax_flags(rng, tmp_path):
    from ft_sgemm_tpu.ops.attention import make_ft_attention

    log = tmp_path / "attn.jsonl"
    telemetry.configure(log, log_clean=True)
    attn = make_ft_attention(softmax_fault=("post", 1, 2, 5.0))
    q = rng.standard_normal((64, 64)).astype(np.float32)
    k = rng.standard_normal((64, 64)).astype(np.float32)
    v = rng.standard_normal((64, 32)).astype(np.float32)
    res = attn(q, k, v)
    telemetry.disable()
    (ev,) = list(read_events(log))
    assert ev.op == "ft_attention"
    assert ev.extra["softmax_flags"] == int(res.softmax_flags) > 0
    assert ev.outcome == "uncorrectable"  # flagged softmax row: unverified


def test_jsonl_roundtrip_via_cli_summarizer(rng, tmp_path, capsys):
    from ft_sgemm_tpu import cli

    log = tmp_path / "faults.jsonl"
    telemetry.configure(log, measure_residual=True, log_clean=True)
    a, b, c = _inputs(rng)
    res = ft_sgemm(a, b, c, TILE, inject=InjectionSpec(enabled=True))
    telemetry.disable()

    rc = cli.main(["cli", "telemetry", str(log)])
    out = capsys.readouterr().out
    assert rc == 0
    assert f"detected: {int(res.num_detected)}" in out
    assert "per-op:" in out
    assert "residual histogram" in out
    # Missing file: usage error, not a traceback.
    assert cli.main(["cli", "telemetry", str(tmp_path / "nope.jsonl")]) == 2


def test_sink_skips_torn_and_foreign_lines(tmp_path):
    log = tmp_path / "log.jsonl"
    sink = JsonlSink(log)
    sink.write(FaultEvent(outcome="corrected", op="x", detected=1,
                          corrected=1))
    sink.close()
    with open(log, "a") as fh:
        fh.write('{"unrelated": true}\n')
        fh.write('{"outcome": "corrected", "op": "y"')  # torn tail
    events = list(read_events(log))
    assert [e.op for e in events] == ["x"]


def test_step_events_and_set_step(tmp_path):
    log = tmp_path / "steps.jsonl"
    telemetry.configure(log)
    telemetry.set_step(17)
    telemetry.record_step_event("retry", uncorrectable=2)
    telemetry.record_step_event("restore", step=18,
                                extra={"restored_step": 9})
    telemetry.disable()
    retry, restore = list(read_events(log))
    assert retry.outcome == "retry" and retry.step == 17
    assert retry.uncorrectable == 2
    assert restore.step == 18 and restore.extra["restored_step"] == 9
    reg = telemetry.get_registry()
    assert reg.total("ft_step_events", outcome="retry") == 1


def test_format_summary_handles_empty_stream():
    text = format_summary(summarize_events([]))
    assert "events: 0" in text
    assert "no residual observations" in text


def test_invalid_outcome_rejected():
    with pytest.raises(ValueError, match="outcome"):
        FaultEvent(outcome="exploded", op="x")


def test_session_context_manager(rng, tmp_path):
    log = tmp_path / "s.jsonl"
    a, b, c = _inputs(rng)
    with telemetry.session(log):
        assert telemetry.enabled()
        ft_sgemm(a, b, c, TILE, inject=InjectionSpec(enabled=True))
    assert not telemetry.enabled()
    assert len(list(read_events(log))) == 1


def test_measure_output_residual_flags_corruption(rng):
    a, b, c = _inputs(rng)
    clean = np.asarray(a @ b.T, dtype=np.float32)
    noise = telemetry.measure_output_residual(clean, a, b)
    corrupted = clean.copy()
    corrupted[3, 7] += 1e4
    fault = telemetry.measure_output_residual(corrupted, a, b)
    assert noise < 1.0 < fault
    assert fault == pytest.approx(1e4, rel=0.01)
