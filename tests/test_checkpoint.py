"""Checkpoint/resume: the ABFT clean-state gate and sharded round-trips.

The reference has nothing to mirror here (SURVEY.md §5: no checkpointing);
these tests pin the framework's own contract — only verified-clean states
persist, restore reproduces exact bits, and sharded pytrees round-trip on
a multi-device mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ft_sgemm_tpu.checkpoint import FtCheckpointer, UncleanStateError


def _state(seed=0):
    k = jax.random.key(seed)
    return {
        "params": {"w": jax.random.normal(k, (8, 8)),
                   "b": jnp.zeros((8,))},
        "step_count": jnp.asarray(3),
    }


def test_save_restore_roundtrip(tmp_path):
    state = _state()
    with FtCheckpointer(tmp_path / "ck") as ck:
        assert ck.save(0, state, uncorrectable=0)
        ck.wait()
        step, got = ck.restore_latest(jax.tree.map(jnp.zeros_like, state))
    assert step == 0
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(got)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_unclean_state_is_refused(tmp_path):
    with FtCheckpointer(tmp_path / "ck") as ck:
        assert not ck.save(0, _state(), uncorrectable=1)
        assert ck.latest_step is None
        # Pytree counts: any nonzero leaf blocks (e.g. ft_counts plus the
        # backward sink's [det, unc]).
        counts = {"layer0": {"uncorrectable": jnp.asarray([0, 2])}}
        assert not ck.save(0, _state(), uncorrectable=counts)
        # force bypasses the gate for externally-verified states.
        assert ck.save(0, _state(), uncorrectable=1, force=True)
        ck.wait()
        assert ck.latest_step == 0


def test_strict_mode_raises(tmp_path):
    with FtCheckpointer(tmp_path / "ck", strict=True) as ck:
        with pytest.raises(UncleanStateError):
            ck.save(0, _state(), uncorrectable=jnp.asarray(1))


def test_total_count_match_filter():
    from ft_sgemm_tpu.checkpoint import total_count

    tree = {"a": {"uncorrectable": jnp.asarray([2, 1]),
                  "detections": jnp.asarray(7)}}
    assert total_count(tree) == 10
    assert total_count(tree, "uncorrectable") == 3
    assert total_count(tree, "detections") == 7
    # A bare leaf has no key paths: filtering it must be loud, never a
    # silent zero.
    assert total_count(jnp.asarray([3, 1])) == 4
    with pytest.raises(ValueError, match="NAMED"):
        total_count(jnp.asarray([3, 1]), "uncorrectable")


def test_gate_rejects_unfiltered_report_trees(tmp_path):
    """Corrected detections are the SUCCESS case: a gate fed the full
    report tree must reject it loudly, not block every save forever."""
    with FtCheckpointer(tmp_path / "ck") as ck:
        report = {"layer0": {"detections": jnp.asarray(4),
                             "uncorrectable": jnp.asarray(0)}}
        with pytest.raises(ValueError, match="UNCORRECTABLE counts only"):
            ck.save(0, _state(), uncorrectable=report)


def test_total_count_match_rejects_unnamed_sequences():
    from ft_sgemm_tpu.checkpoint import total_count

    with pytest.raises(ValueError, match="NAMED"):
        total_count([jnp.asarray(3), jnp.asarray(1)], "uncorrectable")
    # Mixed trees: a name-less leaf anywhere must be loud, not silently
    # dropped from the filtered sum.
    with pytest.raises(ValueError, match="NAMED"):
        total_count(({"uncorrectable": jnp.asarray(1)}, jnp.asarray(2)),
                    "uncorrectable")
    # Named path through a dict of lists is fine (the dict key names it).
    assert total_count({"uncorrectable": [jnp.asarray(1),
                                          jnp.asarray(2)]},
                       "uncorrectable") == 3


def test_force_bypasses_gate_validation_too(tmp_path):
    """force=True is the documented escape hatch for externally-verified
    states: it must skip the unfiltered-report rejection as well."""
    with FtCheckpointer(tmp_path / "ck") as ck:
        report = {"detections": jnp.asarray(4),
                  "uncorrectable": jnp.asarray(1)}
        assert ck.save(0, _state(), uncorrectable=report, force=True)
        ck.wait()
        assert ck.latest_step == 0


def test_save_forwards_orbax_verdict(tmp_path):
    """orbax skips saves at steps <= latest_step; save() must say so
    rather than claiming the state persisted."""
    with FtCheckpointer(tmp_path / "ck") as ck:
        assert ck.save(5, _state())
        ck.wait()
        assert not ck.save(4, _state())
        assert ck.latest_step == 5


def test_restore_latest_without_checkpoints_returns_target(tmp_path):
    target = _state()
    with FtCheckpointer(tmp_path / "ck") as ck:
        step, got = ck.restore_latest(target)
    assert step is None and got is target


def test_retention_keeps_newest(tmp_path):
    with FtCheckpointer(tmp_path / "ck", max_to_keep=2) as ck:
        for s in range(4):
            assert ck.save(s, _state(seed=s))
        ck.wait()
        assert ck.latest_step == 3
        step, got = ck.restore_latest(_state())
        assert step == 3
        want = _state(seed=3)
        np.testing.assert_array_equal(np.asarray(got["params"]["w"]),
                                      np.asarray(want["params"]["w"]))


def test_sharded_roundtrip(tmp_path):
    """Mesh-sharded arrays restore with their sharding, without a gather
    through one host buffer (orbax handles distributed pytrees)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    if len(devs) < 4:
        pytest.skip("needs the 8-virtual-device CPU mesh")
    mesh = Mesh(np.array(devs[:4]), ("x",))
    sh = NamedSharding(mesh, P("x"))
    x = jax.device_put(jnp.arange(16.0).reshape(4, 4), sh)
    state = {"x": x}
    with FtCheckpointer(tmp_path / "ck") as ck:
        assert ck.save(0, state)
        ck.wait()
        ref = {"x": jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh)}
        got = ck.restore(0, ref)
    np.testing.assert_array_equal(np.asarray(got["x"]), np.asarray(x))
    assert got["x"].sharding == sh
