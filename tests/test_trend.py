"""Trend-gate contract: rolling-window verdict math, insufficient-data
semantics, drift detection, and the CLI exit-code acceptance pins
(injected >=20% headline slowdown exits nonzero; flat-noise history
exits 0; insufficient data NEVER fails)."""

import json
import os

import pytest

from ft_sgemm_tpu.cli import main as cli_main
from ft_sgemm_tpu.perf import ledger, trend

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _entry(run_id, value, *, metric="headline_gflops", platform="v5e",
           **ctx):
    return ledger.ingest(
        {"metric": metric, "value": value, "unit": "GFLOPS",
         "context": dict({"platform_used": "tpu",
                          "device_kind": platform}, **ctx)},
        run_id=run_id)


def _ledger_file(tmp_path, entries, name="led.jsonl"):
    path = str(tmp_path / name)
    for e in entries:
        ledger.append(path, e)
    return path


# ---------------------------------------------------------------------------
# Verdict math
# ---------------------------------------------------------------------------


def test_regression_verdict_on_20pct_slowdown():
    j = trend.judge_series([100.0, 101.0, 99.0, 100.0, 79.0],
                           higher_is_better=True)
    assert j["verdict"] == trend.VERDICT_REGRESSION
    assert j["delta"] < -0.2
    assert j["window_n"] == 4


def test_improvement_and_direction_flip():
    up = trend.judge_series([100.0, 100.0, 100.0, 130.0],
                            higher_is_better=True)
    assert up["verdict"] == trend.VERDICT_IMPROVEMENT
    # seconds series: LOWER is better, same numbers regress.
    down = trend.judge_series([100.0, 100.0, 100.0, 130.0],
                              higher_is_better=False)
    assert down["verdict"] == trend.VERDICT_REGRESSION


def test_flat_inside_noise_band():
    j = trend.judge_series([100.0, 104.0, 96.0, 100.0, 97.0],
                           higher_is_better=True)
    assert j["verdict"] == trend.VERDICT_FLAT
    # The band widened past the floor by the window's own noise.
    assert j["tolerance"] >= trend.DEFAULT_REL_FLOOR


def test_noisy_history_widens_tolerance_over_floor():
    noisy = [100.0, 140.0, 60.0, 120.0, 80.0, 100.0]
    j = trend.judge_series(noisy, higher_is_better=True)
    assert j["tolerance"] > 0.5  # 3 sigma of that spread
    assert j["verdict"] == trend.VERDICT_FLAT


@pytest.mark.parametrize("values,reason_frag", [
    ([], "empty_series"),
    ([100.0], "window_n=0"),
    ([100.0, 101.0], "window_n=1"),          # single-run window
    ([100.0, 101.0, 99.0], "window_n=2"),
    ([None, None, None, 100.0], "window_n=0"),  # nulls never feed model
    ([100.0, 101.0, 99.0, 100.0, None], "latest_null"),
])
def test_insufficient_data_cases(values, reason_frag):
    j = trend.judge_series(values, higher_is_better=True)
    assert j["verdict"] == trend.VERDICT_INSUFFICIENT
    assert reason_frag in j["reason"]


def test_zero_window_mean_is_insufficient_not_crash():
    j = trend.judge_series([0.0, 0.0, 0.0, 5.0], higher_is_better=True)
    assert j["verdict"] == trend.VERDICT_INSUFFICIENT
    assert j["reason"] == "zero_window_mean"


def test_window_limits_history():
    # Ancient bad values fall out of the window: only the last `window`
    # non-null points feed the model.
    vals = [10.0] * 5 + [100.0, 101.0, 99.0, 100.0]
    j = trend.judge_series(vals, higher_is_better=True, window=3)
    assert j["verdict"] == trend.VERDICT_FLAT
    assert j["window_n"] == 3
    assert abs(j["mean"] - 100.0) < 2.0


def test_moments_layout_matches_monitor():
    """The (n, sum, sumsq) accumulator is the PR-7 streaming-moments
    layout — same mean/std as the monitor's per-device accumulator."""
    from ft_sgemm_tpu.telemetry.monitor import _Moments

    vals = [1.0, 2.0, 3.5, -1.0]
    a, b = trend.Moments(vals), _Moments()
    for v in vals:
        b.observe(v)
    assert (a.n, a.sum, a.sumsq) == (b.n, b.sum, b.sumsq)
    assert a.mean == b.mean and a.std == b.std


# ---------------------------------------------------------------------------
# Series collection: platforms separate, nulls recorded, drift series
# ---------------------------------------------------------------------------


def test_platforms_make_separate_series():
    entries = ([_entry(f"a{i}", 100.0 + i, platform="v5e")
                for i in range(4)]
               + [_entry(f"b{i}", 50.0, platform="cpu")
                  for i in range(2)])
    series = trend.collect_series(entries)
    assert "headline_gflops@v5e" in series
    assert "headline_gflops@cpu" in series
    assert len(series["headline_gflops@v5e"]["points"]) == 4
    assert len(series["headline_gflops@cpu"]["points"]) == 2


def test_null_headline_runs_are_null_points():
    """The r02–r05 class: a bench run whose metric exists but measured
    null lands as a null point — the latest-run verdict must say
    insufficient (latest_null), not silently judge the previous run."""
    entries = [_entry(f"r{i}", 100.0) for i in range(4)]
    entries.append(_entry("killed", None,
                          errors={"worker_rc": "killed"}))
    report = trend.trend_report(entries)
    row = [r for r in report["rows"]
           if r["series"] == "headline_gflops@v5e"][0]
    assert row["verdict"] == trend.VERDICT_INSUFFICIENT
    assert row["reason"] == "latest_null"
    assert row["latest_run"] == "killed"
    assert trend.exit_code(report) == 0  # never fails a gate


def test_fault_rate_and_slo_burn_drift():
    def fc_entry(run_id, unc, burn):
        doc = {"metric": "serve_goodput_rps", "value": 10.0,
               "unit": "requests/s",
               "context": {"serve": True, "platform_used": "cpu",
                           "device_kind": "cpu",
                           "fault_counters": {"calls": 1000,
                                              "detections": 10,
                                              "uncorrectable": unc},
                           "slo": {"status": "OK", "burn_rate": burn,
                                   "budget_remaining": 0.5}}}
        return ledger.ingest(doc, run_id=run_id)

    # Stable fault-rate/burn history, then both spike in the latest run.
    entries = [fc_entry(f"r{i}", 1, 0.1) for i in range(5)]
    entries.append(fc_entry("spike", 40, 3.0))
    report = trend.trend_report(entries)
    by_series = {r["series"]: r for r in report["rows"]}
    fr = by_series["fault_rate@cpu"]
    burn = by_series["slo_burn@cpu"]
    assert fr["family"] == "drift" and burn["family"] == "drift"
    assert fr["verdict"] == trend.VERDICT_REGRESSION
    assert burn["verdict"] == trend.VERDICT_REGRESSION
    assert trend.exit_code(report) == 1
    # Flat drift history stays flat.
    flat = trend.trend_report([fc_entry(f"f{i}", 1, 0.1)
                               for i in range(6)])
    assert trend.exit_code(flat) == 0


# ---------------------------------------------------------------------------
# CLI gate acceptance pins
# ---------------------------------------------------------------------------


def test_cli_trend_gate_regression_exits_nonzero(tmp_path, capsys):
    """ISSUE 10 acceptance: a synthetic ledger with an injected >=20%
    headline slowdown exits nonzero with a regression verdict."""
    entries = [_entry(f"r{i}", v) for i, v in
               enumerate([25600.0, 25400.0, 25800.0, 25500.0])]
    entries.append(_entry("slow", 25600.0 * 0.78))
    path = _ledger_file(tmp_path, entries)
    rc = cli_main(["cli", "trend", path, "--gate"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "regression" in out
    assert "-2" in out  # the ~-22% delta is printed
    # Without --gate the same report is informational (exit 0).
    assert cli_main(["cli", "trend", path]) == 0


def test_cli_trend_gate_flat_noise_exits_zero(tmp_path, capsys):
    entries = [_entry(f"r{i}", 25600.0 * (1.0 + 0.02 * ((-1) ** i)))
               for i in range(6)]
    path = _ledger_file(tmp_path, entries)
    rc = cli_main(["cli", "trend", path, "--gate"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "regression" not in out.replace("regression=0", "")


def test_cli_trend_gate_insufficient_data_never_fails(tmp_path, capsys):
    # The committed-seed shape: nulls and single runs everywhere.
    entries = [_entry("r0", None), _entry("r1", 100.0),
               _entry("r2", 55.0, metric="other_gflops",
                      platform="cpu")]
    path = _ledger_file(tmp_path, entries)
    rc = cli_main(["cli", "trend", path, "--gate"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "insufficient_data" in out


def test_cli_trend_over_committed_ledger_gates_clean(capsys):
    """The REAL committed ledger (mostly-null r01–r05 + probes) must
    read as insufficient data / flat — never a regression at seed."""
    rc = cli_main(["cli", "trend", os.path.join(REPO, "LEDGER.jsonl"),
                   "--gate"])
    assert rc == 0


def test_cli_trend_unreadable_ledger_exits_2(tmp_path):
    assert cli_main(["cli", "trend",
                     str(tmp_path / "missing.jsonl"), "--gate"]) == 2


def test_cli_trend_json_format(tmp_path, capsys):
    path = _ledger_file(tmp_path, [_entry(f"r{i}", 100.0)
                                   for i in range(4)])
    rc = cli_main(["cli", "trend", path, "--format=json"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["counts"]["flat"] == 1
    assert doc["rows"][0]["series"] == "headline_gflops@v5e"


def test_cli_trend_param_flags(tmp_path, capsys):
    # min-runs raised past the history -> insufficient; floor widened
    # past the injected drop -> flat.
    entries = [_entry(f"r{i}", v) for i, v in
               enumerate([100.0, 100.0, 100.0, 100.0, 80.0])]
    path = _ledger_file(tmp_path, entries)
    assert cli_main(["cli", "trend", path, "--gate"]) == 1
    capsys.readouterr()
    assert cli_main(["cli", "trend", path, "--gate",
                     "--min-runs=10"]) == 0
    capsys.readouterr()
    assert cli_main(["cli", "trend", path, "--gate", "--floor=0.3"]) == 0
    capsys.readouterr()
    assert cli_main(["cli", "trend", path, "--gate",
                     "--floor=junk"]) == 2
