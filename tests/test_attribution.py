"""Per-device SDC localization over the simulated 8-device CPU mesh.

The distributed paths must answer "WHICH chip produced this fault":
inject on exactly one shard (``inject_coords``), then assert the merged
telemetry names that shard's device, host, and mesh coordinates — plus
the two-host JSONL-shard merge that reassembles a pod-wide view from
per-process event logs (``telemetry/aggregate.py``; DESIGN.md §8).
"""

import json

import numpy as np
import pytest

from ft_sgemm_tpu import InjectionSpec, sgemm_reference, telemetry
from ft_sgemm_tpu.configs import KernelShape
from ft_sgemm_tpu.parallel import (
    make_mesh,
    make_multihost_mesh,
    make_ring_mesh,
    multihost_ft_sgemm,
    ring_ft_attention,
    ring_ft_sgemm,
    sharded_ft_sgemm,
)
from ft_sgemm_tpu.telemetry import aggregate, read_events
from ft_sgemm_tpu.utils import generate_random_matrix, verify_matrix

ALPHA, BETA = 1.0, -1.5
TILE = KernelShape("t128", 128, 128, 128, (0,) * 7)
INJ = InjectionSpec(enabled=True, every=1, magnitude=10000.0)


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.reset()
    yield
    telemetry.reset()


def _inputs(m, n, k, seed=11):
    rng = np.random.default_rng(seed)
    return (
        generate_random_matrix(m, k, rng=rng),
        generate_random_matrix(n, k, rng=rng),
        generate_random_matrix(m, n, rng=rng),
    )


def test_sharded_injection_localizes_to_target_shard(tmp_path):
    """Inject on ONE shard of the 2x4 mesh: the output must still verify
    (the fault is corrected locally) and the merged event must name
    exactly that shard's device and (x, y) coordinates."""
    log = tmp_path / "faults.jsonl"
    mesh = make_mesh(8)  # 2 x 4
    a, b, c = _inputs(256, 128, 512)
    target = (1, 2)
    with telemetry.session(log):
        res = sharded_ft_sgemm(a, b, c, mesh, TILE, alpha=ALPHA, beta=BETA,
                               inject=INJ, inject_coords=target)
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok, f"{nbad} corrupted elements survived"
    # Only the target device injects: local k-steps = 512/4/128 = 1.
    assert int(res.num_detected) == 1

    (ev,) = list(read_events(log))
    assert ev.op == "sharded_ft_sgemm" and ev.outcome == "corrected"
    assert ev.host == 0 and ev.ts is not None
    assert ev.devices is not None and len(ev.devices) == 1
    entry = ev.devices[0]
    assert entry["coords"] == list(target)
    assert entry["axes"] == ["x", "y"]
    assert entry["detected"] == 1 and entry["uncorrectable"] == 0
    # The entry names the REAL device at mesh position (1, 2).
    assert entry["device"] == str(mesh.devices[1][2])
    assert entry["host"] == 0

    # Registry: per-device series carry the same localization.
    reg = telemetry.get_registry()
    assert reg.total("ft_device_detections") == 1
    assert reg.total("ft_device_detections", coords="1,2") == 1
    assert reg.total("ft_device_detections", coords="0,0") == 0
    # Every device's calls are counted (rates stay computable)...
    assert reg.total("ft_device_calls") == 8
    # ...and the call-level counters are NOT double-counted.
    assert reg.total("ft_detections") == 1


def test_sharded_clean_run_lists_no_devices(tmp_path):
    log = tmp_path / "clean.jsonl"
    mesh = make_mesh(8)
    a, b, c = _inputs(256, 128, 512, seed=5)
    telemetry.configure(log, log_clean=True)
    sharded_ft_sgemm(a, b, c, mesh, TILE, alpha=ALPHA, beta=BETA)
    telemetry.disable()
    (ev,) = list(read_events(log))
    assert ev.outcome == "clean"
    assert ev.devices is None  # pod-scale events stay small when clean
    # ...but per-device call counts still landed in the registry.
    assert telemetry.get_registry().total("ft_device_calls") == 8


def test_ring_injection_localizes_to_ring_position(tmp_path):
    log = tmp_path / "ring.jsonl"
    mesh = make_ring_mesh(8)
    a, b, c = _inputs(256, 256, 512)
    with telemetry.session(log):
        res = ring_ft_sgemm(a, b, c, mesh, TILE, alpha=ALPHA, beta=BETA,
                            inject=INJ, inject_coords=(3,))
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok, f"{nbad} corrupted elements survived the ring"
    assert int(res.num_detected) > 0
    (ev,) = list(read_events(log))
    assert ev.op == "ring_ft_sgemm"
    (entry,) = ev.devices
    assert entry["coords"] == [3] and entry["axes"] == ["x"]
    assert entry["detected"] == int(res.num_detected)
    assert entry["device"] == str(mesh.devices[3])


def test_multihost_injection_localizes_across_host_axis(tmp_path):
    """(host, x, y) mesh: the event entry names the 3-axis coordinates
    including the host slot — the cross-DCN localization view."""
    log = tmp_path / "mh.jsonl"
    mesh = make_multihost_mesh(hosts=2)  # (2, 2, 2) over 8 CPU devices
    a, b, c = _inputs(256, 128, 512, seed=9)
    target = (1, 0, 1)
    with telemetry.session(log):
        res = multihost_ft_sgemm(a, b, c, mesh, TILE, alpha=ALPHA,
                                 beta=BETA, inject=INJ,
                                 inject_coords=target)
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok, f"{nbad} bad"
    assert int(res.num_detected) > 0
    (ev,) = list(read_events(log))
    assert ev.op == "multihost_ft_sgemm"
    (entry,) = ev.devices
    assert entry["coords"] == list(target)
    assert entry["axes"] == ["host", "x", "y"]
    assert entry["device"] == str(mesh.devices[1][0][1])


def test_ring_attention_injection_localizes(tmp_path):
    log = tmp_path / "attn.jsonl"
    mesh = make_ring_mesh(8)
    rng = np.random.default_rng(2)
    q = rng.standard_normal((256, 64)).astype(np.float32)
    k = rng.standard_normal((256, 64)).astype(np.float32)
    v = rng.standard_normal((256, 64)).astype(np.float32)
    with telemetry.session(log):
        res = ring_ft_attention(q, k, v, mesh, inject=INJ,
                                inject_coords=(5,))
    assert int(res.detections) > 0
    assert int(res.uncorrectable) == 0
    (ev,) = list(read_events(log))
    assert ev.op == "ring_ft_attention"
    (entry,) = ev.devices
    assert entry["coords"] == [5]
    assert entry["detected"] == int(res.detections)


def test_inject_coords_arity_mismatch_raises():
    mesh = make_mesh(8)
    a, b, c = _inputs(256, 128, 512)
    with pytest.raises(ValueError, match="one coordinate per mesh axis"):
        sharded_ft_sgemm(a, b, c, mesh, TILE, inject=INJ,
                         inject_coords=(1,))


# -- two-host JSONL-shard merge (telemetry/aggregate.py) --------------------


def _shard_event(host, device, coords, detected, unc=0, ts=0.0,
                 residual=None):
    d = {"outcome": "uncorrectable" if unc else "corrected",
         "op": "sharded_ft_sgemm", "detected": detected,
         "corrected": detected, "uncorrectable": unc, "host": host,
         "ts": ts,
         "devices": [{"host": host, "device": device, "id": 0,
                      "coords": coords, "axes": ["x", "y"],
                      "detected": detected, "uncorrectable": unc}]}
    if residual is not None:
        d["residual"] = residual
    return d


def test_two_host_shard_merge_localizes_and_ranks(tmp_path):
    """Each process of a multi-host run writes its own shard listing only
    its devices; the merge must reassemble the pod view, order by ts,
    and rank the faultiest chip first."""
    shard0 = tmp_path / "host0.jsonl"
    shard1 = tmp_path / "host1.jsonl"
    shard0.write_text(
        json.dumps(_shard_event(0, "TPU_0", [0, 0], 1, ts=3.0)) + "\n"
        + json.dumps({"outcome": "clean", "op": "sharded_ft_sgemm",
                      "host": 0, "ts": 1.0}) + "\n")
    shard1.write_text(
        json.dumps(_shard_event(1, "TPU_5", [1, 1], 4, unc=2, ts=2.0,
                                residual=1.2e4)) + "\n"
        + json.dumps(_shard_event(1, "TPU_5", [1, 1], 3, ts=4.0)) + "\n")
    events = aggregate.merge_shards([shard0, shard1])
    assert [e.ts for e in events] == [1.0, 2.0, 3.0, 4.0]  # interleaved

    table = aggregate.device_table(events)
    assert table["calls"] == 4
    assert set(table["devices"]) == {(0, "TPU_0"), (1, "TPU_5")}
    bad = table["devices"][(1, "TPU_5")]
    assert bad["detected"] == 7 and bad["uncorrectable"] == 2
    assert bad["events"] == 2 and bad["coords"] == [1, 1]
    assert bad["max_residual"] == pytest.approx(1.2e4)

    ranked = aggregate.rank_devices(table)
    assert ranked[0][0] == (1, "TPU_5")  # uncorrectable outranks all
    text = aggregate.format_device_table(table, ranked=True)
    assert "TPU_5" in text and "(x=1,y=1)" in text
    assert text.index("TPU_5") < text.index("TPU_0")


def test_merge_tolerates_pre_attribution_logs(tmp_path):
    """Old logs (no ts, no devices) still merge: the event's own device
    label becomes a synthetic attribution row."""
    old = tmp_path / "old.jsonl"
    old.write_text(json.dumps({"outcome": "corrected", "op": "x",
                               "detected": 2, "corrected": 2,
                               "device": "mesh2x4"}) + "\n")
    events = aggregate.merge_shards([old])
    table = aggregate.device_table(events)
    assert table["devices"][(None, "mesh2x4")]["detected"] == 2
    assert "mesh2x4" in aggregate.format_device_table(table)


def test_cli_by_device_and_attribute(tmp_path, capsys):
    from ft_sgemm_tpu import cli

    log = tmp_path / "ev.jsonl"
    log.write_text(
        json.dumps(_shard_event(0, "TPU_3", [0, 1], 5, ts=1.0)) + "\n")
    assert cli.main(["cli", "telemetry", str(log), "--by-device"]) == 0
    out = capsys.readouterr().out
    assert "TPU_3" in out and "(x=0,y=1)" in out
    assert cli.main(["cli", "attribute", str(log)]) == 0
    out = capsys.readouterr().out
    assert "TPU_3" in out and "1 shard(s)" in out
    assert cli.main(["cli", "attribute",
                     str(tmp_path / "missing.jsonl")]) == 2
