"""FtDense: ABFT-protected flax layer — training-framework integration."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

flax = pytest.importorskip("flax")
optax = pytest.importorskip("optax")

from ft_sgemm_tpu import InjectionSpec  # noqa: E402
from ft_sgemm_tpu.configs import KernelShape  # noqa: E402
from ft_sgemm_tpu.nn import COUNTS_COLLECTION, FtDense  # noqa: E402
from ft_sgemm_tpu.utils import (  # noqa: E402
    generate_random_matrix,
    verify_matrix,
)

TILE = KernelShape("t128", 128, 128, 128, (0,) * 7)


def _data(batch=128, d_in=128, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(generate_random_matrix(batch, d_in, rng=rng))


def test_forward_matches_plain_dense():
    x = _data()
    layer = FtDense(64, shape=TILE)
    vars_ = layer.init(jax.random.key(0), x)
    got = layer.apply(vars_, x)
    kernel = vars_["params"]["kernel"]
    bias = vars_["params"]["bias"]
    want = np.asarray(x @ kernel + bias)
    ok, nbad, _ = verify_matrix(want, np.asarray(got), verbose=False)
    assert ok, f"{nbad} elements off vs plain dense"


def test_counts_observable_and_faults_corrected():
    x = _data(seed=3)
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    layer = FtDense(128, shape=TILE, inject=inj)
    vars_ = layer.init(jax.random.key(1), x)
    out, mutated = layer.apply(vars_, x, mutable=[COUNTS_COLLECTION])
    counts = mutated[COUNTS_COLLECTION]
    assert int(counts["detections"]) > 0
    assert int(counts["uncorrectable"]) == 0
    clean = layer.apply(
        {"params": vars_["params"]}, x)  # injection corrected away
    kernel = vars_["params"]["kernel"]
    want = np.asarray(x @ kernel + vars_["params"]["bias"])
    for got in (out, clean):
        ok, nbad, _ = verify_matrix(want, np.asarray(got), verbose=False)
        assert ok, f"{nbad} injected faults survived"


def test_counts_dropped_without_mutable():
    x = _data(seed=4)
    layer = FtDense(64, shape=TILE)
    vars_ = layer.init(jax.random.key(2), x)
    out = layer.apply(vars_, x)  # no mutable: counts silently dropped
    assert out.shape == (128, 64)


@pytest.mark.parametrize("threshold", [9500.0, "auto"])
def test_training_step_under_injection(threshold):
    """A jitted optax SGD step through two FtDense layers with every-step
    injection: gradients flow, faults are corrected, loss decreases."""
    import flax.linen as nn_

    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)

    class Model(nn_.Module):
        @nn_.compact
        def __call__(self, x):
            h = jnp.tanh(FtDense(128, shape=TILE, inject=inj,
                                 threshold=threshold)(x))
            return FtDense(128, shape=TILE, inject=inj,
                           threshold=threshold)(h)

    x = _data(seed=5)
    rngw = np.random.default_rng(6)
    y = jnp.asarray(generate_random_matrix(128, 128, rng=rngw))
    model = Model()
    params = model.init(jax.random.key(3), x)["params"]
    tx = optax.sgd(0.5)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state):
        def loss_fn(p):
            out, mut = model.apply({"params": p}, x,
                                   mutable=[COUNTS_COLLECTION])
            return jnp.mean((out - y) ** 2), mut[COUNTS_COLLECTION]

        (loss, counts), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss, counts

    params1, opt_state, l0, counts = step(params, opt_state)
    assert any(int(jax.tree_util.tree_leaves(c)[0]) > 0
               for c in jax.tree_util.tree_leaves(counts)), (
        "per-layer fault counts must be observable in the training step")
    losses = [float(l0)]
    for _ in range(12):
        params1, opt_state, loss, _ = step(params1, opt_state)
        losses.append(float(loss))
    # Strict monotone decrease is the fault-freedom signature: a fault
    # surviving into gradients or activations spikes the loss by orders
    # of magnitude (observed 1e3-1e6 with correction disabled).
    assert all(b < a for a, b in zip(losses, losses[1:])), losses
    assert losses[-1] < 0.95 * losses[0], losses


def test_counts_accumulate_across_tied_invocations():
    """ADVICE r3 (medium): a module instance applied more than once per
    step (weight tying) must SUM its counts across invocations — a later
    clean call's 0 must not overwrite an earlier call's nonzero report."""
    import flax.linen as nn_

    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)

    class Tied(nn_.Module):
        @nn_.compact
        def __call__(self, x):
            layer = FtDense(128, shape=TILE, inject=inj)
            return layer(layer(x))  # same instance, two invocations

    x = _data(seed=8)
    model = Tied()
    vars_ = model.init(jax.random.key(5), x)
    # Counts are per-apply: the init trace must not pre-load them.
    assert COUNTS_COLLECTION not in vars_, list(vars_)
    _, mutated = model.apply(vars_, x, mutable=[COUNTS_COLLECTION])
    counts = mutated[COUNTS_COLLECTION]
    [det] = jax.tree_util.tree_leaves(counts["FtDense_0"]["detections"])
    # Injection fires in BOTH invocations; a latest-wins reducer would
    # report only the second call's count.
    single = FtDense(128, shape=TILE, inject=inj)
    svars = single.init(jax.random.key(5), x)
    _, smut = single.apply(svars, x, mutable=[COUNTS_COLLECTION])
    [sdet] = jax.tree_util.tree_leaves(
        smut[COUNTS_COLLECTION]["detections"])
    assert int(det) == 2 * int(sdet) > 0, (det, sdet)
    # And per-apply means NOT cumulative across applies: a second apply
    # from the same (params-only) variables reports the same counts.
    _, mut2 = model.apply({"params": vars_["params"]}, x,
                          mutable=[COUNTS_COLLECTION])
    [det2] = jax.tree_util.tree_leaves(
        mut2[COUNTS_COLLECTION]["FtDense_0"]["detections"])
    assert int(det2) == int(det), (det2, det)


def test_bf16_in_dtype_keeps_activation_dtype():
    x = _data(seed=7).astype(jnp.bfloat16)
    layer = FtDense(64, shape=TILE, in_dtype="bfloat16")
    vars_ = layer.init(jax.random.key(4), x)
    out = layer.apply(vars_, x)
    assert out.dtype == jnp.bfloat16
    kernel = vars_["params"]["kernel"]
    want = np.asarray(x.astype(jnp.float32)
                      @ np.asarray(kernel)).astype(np.float32)
    got = np.asarray(out.astype(jnp.float32))
    # bf16 rounding tolerance: inputs and output each round once.
    assert np.allclose(got, want + np.asarray(vars_["params"]["bias"]),
                       rtol=3e-2, atol=3e-2)
