"""Persistent compile-cache observability (perf/compile_cache.py).

Contract: the cache location resolves env-first (the hermetic pin), a
failure to enable is a NAMED reason rather than a swallowed exception,
and hits/misses/bytes-written are counted from the runtime's own
monitoring events + a directory snapshot — the numbers the bench
artifact context and RunReport manifest embed, and the CI double-smoke
job asserts warm-start on.
"""

import jax
import jax.numpy as jnp
import pytest

from ft_sgemm_tpu.perf import compile_cache
from ft_sgemm_tpu.telemetry.registry import MetricsRegistry


@pytest.fixture
def cache_restore():
    """Restore the process-global cache config after a test enables it
    (the suite runs with FT_SGEMM_COMPILE_CACHE=0 — see conftest)."""
    yield
    compile_cache.disable()
    compile_cache._reset_for_tests()


def test_env_off_pin_disables_with_named_reason(monkeypatch):
    monkeypatch.setenv(compile_cache.ENV_COMPILE_CACHE, "0")
    status = compile_cache.enable()
    assert status["enabled"] is False
    assert compile_cache.ENV_COMPILE_CACHE in status["reason"]
    # stats() degrades, never raises.
    s = compile_cache.stats()
    assert s["enabled"] is False and s["bytes_written"] is None


def test_resolve_order_env_then_default(monkeypatch):
    monkeypatch.setenv(compile_cache.ENV_COMPILE_CACHE, "/some/dir")
    assert compile_cache.resolve_dir("/caller/default") == ("/some/dir",
                                                           None)
    monkeypatch.delenv(compile_cache.ENV_COMPILE_CACHE)
    assert compile_cache.resolve_dir("/caller/default") == (
        "/caller/default", None)
    path, reason = compile_cache.resolve_dir()
    assert path == compile_cache.default_cache_dir() and reason is None


def test_unwritable_dir_is_a_named_failure(monkeypatch, tmp_path):
    target = tmp_path / "blocked"
    target.write_text("a file where a directory must go")
    monkeypatch.setenv(compile_cache.ENV_COMPILE_CACHE, str(target))
    status = compile_cache.enable()
    assert status["enabled"] is False
    assert status["reason"], "failure must carry a named reason"
    compile_cache._reset_for_tests()


def test_miss_then_hit_counting_and_bytes_written(monkeypatch, tmp_path,
                                                  cache_restore):
    monkeypatch.setenv(compile_cache.ENV_COMPILE_CACHE,
                       str(tmp_path / "jaxcache"))
    status = compile_cache.enable()
    assert status["enabled"] is True, status

    @jax.jit
    def f(x):
        return (x @ x.T).sum() * 3.0

    x = jnp.ones((160, 160))
    float(f(x))  # cold: persistent-cache miss, entry written
    s1 = compile_cache.stats()
    assert s1["misses"] >= 1
    assert s1["files_written"] >= 1 and s1["bytes_written"] > 0

    # Drop the in-memory jit cache: the recompile must be served from
    # the persistent cache — the warm-start path a bench relaunch takes.
    jax.clear_caches()
    float(f(x))
    s2 = compile_cache.stats()
    assert s2["hits"] >= 1, s2
    assert s2["requests"] >= s2["hits"] + s2["misses"] - 1

    reg = MetricsRegistry()
    compile_cache.record(registry=reg)
    names = {m["name"] for m in reg.collect()}
    assert {"compile_cache.enabled", "compile_cache.hits",
            "compile_cache.misses"} <= names


def test_second_enable_with_same_path_keeps_counting(monkeypatch, tmp_path,
                                                     cache_restore):
    monkeypatch.setenv(compile_cache.ENV_COMPILE_CACHE,
                       str(tmp_path / "jaxcache"))
    compile_cache.enable()

    @jax.jit
    def g(x):
        return (x * 2.0).sum()

    float(g(jnp.ones((96, 96))))
    # Re-enable (a resumed bench worker does this): counters reset, the
    # snapshot re-bases, and traffic after it still counts.
    compile_cache.enable()
    s = compile_cache.stats()
    assert s["enabled"] and s["hits"] == 0 and s["misses"] == 0
    jax.clear_caches()
    float(g(jnp.ones((96, 96))))
    assert compile_cache.stats()["hits"] >= 1
