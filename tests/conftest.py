"""Test harness config: CPU backend with a virtual 8-device mesh.

Tests must run with no TPU attached (SURVEY.md §4 "TPU build test plan"):
Pallas kernels run in interpret mode (auto-selected when the backend isn't
TPU), sharding tests run over 8 virtual CPU devices.
"""

import os
import tempfile

# Force CPU even when a TPU platform is configured in the environment: the
# suite must pass with no TPU attached. TPU validation runs live separately
# (scripts/validate_tpu.py, bench.py).
os.environ["JAX_PLATFORMS"] = "cpu"

# Hermetic autotuner: dispatch consults the tile cache by default
# (ft_sgemm_tpu.tuner), and a developer's ~/.cache entries must never leak
# tuned tiles — and therefore different HLO — into the suite. Tests that
# exercise the tuner monkeypatch this to their own tmp path.
os.environ["FT_SGEMM_TUNER_CACHE"] = os.path.join(
    tempfile.mkdtemp(prefix="ft_sgemm_test_tuner_"), "tuner_cache.json")

# Hermetic compile cache, same pattern: bench.py/prewarm/tune enable the
# persistent XLA compilation cache by default (perf/compile_cache.py),
# and the suite's subprocess runs (bench --smoke, CLI entry points) must
# neither read nor write a developer's ~/.cache executables. Pinned OFF;
# tests that exercise the cache monkeypatch this to their own tmp dir.
os.environ["FT_SGEMM_COMPILE_CACHE"] = "0"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The env var alone is not enough when a TPU PJRT plugin (e.g. the axon
# tunnel) is installed — pin the platform through jax.config as well.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402

assert jax.default_backend() == "cpu", jax.default_backend()
assert len(jax.devices()) == 8, jax.devices()


@pytest.fixture
def rng():
    return np.random.default_rng(10)


def bf16_rounded_oracle(a, b, c, alpha=1.0, beta=-1.5):
    """f32 XLA-dot reference over bf16-rounded A/B — the exact semantics of
    the ``in_dtype="bfloat16"`` kernel path (a bf16 x bf16 product is exact
    in f32, so rounding the inputs once captures the entire precision
    difference; what remains is accumulation-order noise)."""
    import jax.numpy as jnp

    from ft_sgemm_tpu.ops.reference import sgemm_reference

    ar = np.asarray(jnp.asarray(a, jnp.bfloat16).astype(jnp.float32))
    br = np.asarray(jnp.asarray(b, jnp.bfloat16).astype(jnp.float32))
    return np.asarray(sgemm_reference(ar, br, c, alpha, beta))
