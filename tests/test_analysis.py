"""Detection-rate / threshold-calibration analysis tests.

Pins the operating-point math the reference hardcodes (magnitude 1e4 vs
threshold 9.5e3, ``ft_sgemm_huge.cuh:49-51``): clean noise floors sit orders
of magnitude below the threshold, faults above it are always caught, faults
below it are the scheme's documented blind spot.
"""

import numpy as np
import pytest

from ft_sgemm_tpu.analysis import (
    calibrate_threshold,
    detection_rate_sweep,
    measure_noise_floor,
)
from ft_sgemm_tpu.injection import REFERENCE_THRESHOLD
from ft_sgemm_tpu.utils import generate_random_matrix


def _inputs(m, n, k, seed=10):
    rng = np.random.default_rng(seed)
    return (
        generate_random_matrix(m, k, rng=rng),
        generate_random_matrix(n, k, rng=rng),
        generate_random_matrix(m, n, rng=rng),
    )


def test_noise_floor_far_below_reference_threshold():
    a, b, c = _inputs(256, 256, 1024)
    floor = measure_noise_floor(a, b, c)
    # The reference's whole design rests on this separation (SURVEY.md §4
    # "Determinism"): quantized inputs keep f32 checksum noise << 9500.
    assert 0.0 <= floor < REFERENCE_THRESHOLD / 100


def test_calibrate_threshold_orders():
    a, b, c = _inputs(256, 256, 512)
    cal = calibrate_threshold(a, b, c, margin=8.0)
    assert cal.noise_floor <= cal.threshold <= cal.min_detectable
    assert cal.min_detectable == pytest.approx(2 * cal.threshold)
    # A reference-style spec at the calibrated magnitude is valid.
    spec = cal.spec_like(K=512, bk=256)
    assert spec.enabled and spec.magnitude == pytest.approx(cal.min_detectable)


@pytest.mark.parametrize("strategy", ["rowcol", "weighted"])
def test_detection_rate_above_and_below_threshold(strategy):
    a, b, c = _inputs(128, 128, 1024)
    pts = detection_rate_sweep(
        a, b, c, magnitudes=[1.0, 20000.0], shape="small",
        strategy=strategy, num_faults=2,
    )
    below, above = pts
    # Below threshold: designed miss — nothing detected, and the tiny fault
    # doesn't break the 0.01 verify tolerance either.
    assert below.detection_rate == 0.0
    # Above threshold: every fault caught and corrected.
    assert above.detection_rate == pytest.approx(1.0)
    assert above.output_correct, f"{strategy}: corrected output still bad"
    assert above.expected_faults == above.detected > 0


def test_detection_sweep_counts_tiles():
    # 256x256 output with the small shape's 128x128 tiles -> 4 tiles.
    a, b, c = _inputs(256, 256, 512)
    (pt,) = detection_rate_sweep(
        a, b, c, magnitudes=[15000.0], shape="small", num_faults=2,
    )
    assert pt.expected_faults == 4 * 2
    assert pt.detected == pt.expected_faults


def test_calibrated_threshold_catches_calibrated_magnitude():
    a, b, c = _inputs(128, 128, 512)
    cal = calibrate_threshold(a, b, c)
    (pt,) = detection_rate_sweep(
        a, b, c, magnitudes=[cal.min_detectable], shape="small",
        threshold=cal.threshold, num_faults=1,
    )
    assert pt.detection_rate == pytest.approx(1.0)
    assert pt.output_correct


def test_detection_sweep_bf16_catches_reference_magnitude():
    a, b, c = _inputs(256, 256, 512, seed=17)
    pts = detection_rate_sweep(
        a, b, c, magnitudes=[1e5], shape="test", strategy="rowcol",
        num_faults=2, in_dtype="bfloat16")
    assert pts[0].detection_rate == 1.0 and pts[0].output_correct


def test_calibrate_threshold_bf16_noise_floor_stays_f32_class():
    # Checksums see the rounded inputs, so the bf16 noise floor must stay
    # within a small factor of the f32 floor (not the ~100x an fp16-style
    # rounding mismatch would produce).
    a, b, c = _inputs(256, 256, 512, seed=18)
    cal32 = calibrate_threshold(a, b, c)
    cal16 = calibrate_threshold(a, b, c, in_dtype="bfloat16")
    assert cal16.noise_floor < max(cal32.noise_floor, 1e-3) * 50


def test_detection_sweep_accounts_for_shrunk_tiles():
    # Regression: "huge" (512^3) on a 640x640x1024 problem shrinks at run
    # time; expected-fault accounting must follow the effective tile or the
    # rate mis-reports.
    # Reference operating-point magnitude (1e4): far above the threshold yet
    # small enough that the f32 correction residual (~mag * 2^-24) stays
    # inside the verify tolerance.
    a, b, c = _inputs(640, 640, 1024, seed=19)
    pts = detection_rate_sweep(
        a, b, c, magnitudes=[1e4], shape="huge", strategy="rowcol",
        num_faults=2)
    assert pts[0].detection_rate == 1.0 and pts[0].output_correct


def test_estimate_noise_floor_bounds_measurement():
    from ft_sgemm_tpu.analysis import estimate_noise_floor

    a, b, c = _inputs(256, 256, 1024, seed=20)
    est = estimate_noise_floor(a, b, c)
    measured = measure_noise_floor(a, b, c)
    # The closed-form bound must dominate the measured floor while staying
    # far below the reference operating threshold.
    assert measured <= est < REFERENCE_THRESHOLD / 10
    # The beta*C term matters on its own: tiny A/B against a huge C.
    big_c = c * 1e6
    est_big = estimate_noise_floor(a * 1e-3, b * 1e-3, big_c)
    meas_big = measure_noise_floor(a * 1e-3, b * 1e-3, big_c)
    assert meas_big <= est_big
    # And omitting C with beta != 0 is an error, not a silent undershoot.
    import pytest as _pytest

    with _pytest.raises(ValueError, match="beta"):
        estimate_noise_floor(a, b)


def test_estimate_noise_floor_is_calibrated_not_folklore():
    """The closed-form bound must DOMINATE the measured floor (safety) but
    stay within ~20x of it for random-sign data (usefulness): the round-2
    T^1.5 formula overshot by 4-6 orders of magnitude, making the
    estimator useless for calibration. Also checks the biased-data regime
    (same-sign inputs, where cancellation-based scaling would undershoot)
    and the scaling exponent (floors grow ~linearly in size; a T^1.5 model
    would grow the ratio by ~size^2 per doubling)."""
    from ft_sgemm_tpu.analysis import estimate_noise_floor

    rng = np.random.default_rng(21)
    ratios = []
    for size in (256, 512):
        a, b, c = (generate_random_matrix(size, size, rng=rng)
                   for _ in range(3))
        est = estimate_noise_floor(a, b, c)
        meas = measure_noise_floor(a, b, c)
        assert meas <= est, (size, meas, est)
        ratios.append(est / meas)
        assert est / meas < 20.0, (size, est / meas)
    # Scaling sanity: the bound/measured ratio must not explode with size
    # (T^1.5 vs the true ~sqrt(T) would multiply it ~16x per doubling).
    assert ratios[1] / ratios[0] < 4.0, ratios

    # Biased (same-sign) inputs: the cancellation model alone would
    # undershoot; the bias term must keep the bound dominant.
    ab = np.abs(rng.standard_normal((256, 256))).astype(np.float32)
    bb = np.abs(rng.standard_normal((256, 256))).astype(np.float32)
    cb = np.abs(rng.standard_normal((256, 256))).astype(np.float32)
    assert measure_noise_floor(ab, bb, cb) <= estimate_noise_floor(ab, bb, cb)


def test_traced_estimator_matches_numpy_estimator():
    """The jnp estimator behind make_ft_sgemm(threshold='auto') and the
    numpy one documented/calibrated in this module must be the same model:
    a recalibration edit to one that misses the other would silently move
    auto thresholds orders of magnitude off the validated bound."""
    import jax.numpy as jnp
    import pytest as _pytest

    from ft_sgemm_tpu.analysis import estimate_noise_floor
    from ft_sgemm_tpu.ops.common import estimate_noise_floor_jnp

    rng = np.random.default_rng(22)
    a, b, c = (generate_random_matrix(320, 256, rng=rng) for _ in range(3))
    a = a[:, :256]
    v_np = estimate_noise_floor(a, b[:192], c[:, :192],
                                alpha=2.0, beta=-0.5)
    v_jnp = float(estimate_noise_floor_jnp(
        jnp.asarray(a), jnp.asarray(b[:192]), jnp.asarray(c[:, :192]),
        2.0, -0.5))
    assert abs(v_np - v_jnp) / v_np < 1e-3, (v_np, v_jnp)
    # Identical contracts: both refuse beta != 0 without c.
    with _pytest.raises(ValueError, match="beta"):
        estimate_noise_floor_jnp(jnp.asarray(a), jnp.asarray(b), None,
                                 1.0, -1.5)
