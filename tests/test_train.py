"""resilient_step: the retry/restore/raise policy over the report channel.

The step stubs model the kernels' clean-or-reported contract exactly:
they return (new_state, metrics, uncorrectable) and the wrapper must
never let an unverified new_state escape. One integration test runs the
real FtDense step shape end to end.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ft_sgemm_tpu.train import (
    StepReport,
    UncorrectableStepError,
    resilient_step,
)


def _flaky(fail_times):
    """A step that reports on its first `fail_times` calls, then is clean.
    new_state increments only so we can see WHICH attempt's state won."""
    calls = {"n": 0}

    def step(state):
        calls["n"] += 1
        unc = 1 if calls["n"] <= fail_times else 0
        return state + 1, {"loss": 0.5}, unc

    return step, calls


def test_clean_step_passes_through():
    step, calls = _flaky(0)
    new_state, metrics, rep = resilient_step(step, 10)
    assert new_state == 11 and metrics["loss"] == 0.5
    assert calls["n"] == 1
    assert rep.retries == 0 and rep.restored_step is None


def test_transient_report_retries_from_pre_step_state():
    step, calls = _flaky(2)
    new_state, _, rep = resilient_step(step, 10, max_retries=2)
    assert new_state == 11, "retry must re-run from the PRE-step state"
    assert calls["n"] == 3 and rep.retries == 2


def test_persistent_report_raises_without_checkpointer():
    step, _ = _flaky(10)
    with pytest.raises(UncorrectableStepError, match="no clean checkpoint"):
        resilient_step(step, 10, max_retries=1)


def test_persistent_report_restores_then_succeeds(tmp_path):
    from ft_sgemm_tpu.checkpoint import FtCheckpointer

    state0 = {"w": jnp.asarray([1.0, 2.0])}
    with FtCheckpointer(tmp_path / "ck") as ck:
        assert ck.save(7, state0)
        ck.wait()

        seen = []

        def step(state):
            seen.append(np.asarray(state["w"]).copy())
            # Reports until handed the checkpointed state; the "bad"
            # live state never produces a clean step.
            bad = float(state["w"][0]) != 1.0
            return ({"w": state["w"] + 1}, {}, 1 if bad else 0)

        live = {"w": jnp.asarray([99.0, 99.0])}  # corrupted live state
        new_state, _, rep = resilient_step(
            step, live, max_retries=1, checkpointer=ck)
    assert rep.restored_step == 7 and rep.retries == 2
    np.testing.assert_array_equal(np.asarray(new_state["w"]), [2.0, 3.0])
    # Attempts: live, live (retry), then restored.
    assert [s[0] for s in seen] == [99.0, 99.0, 1.0]


def test_failure_after_restore_raises(tmp_path):
    from ft_sgemm_tpu.checkpoint import FtCheckpointer

    always_bad = lambda s: (s, {}, 1)  # noqa: E731
    with FtCheckpointer(tmp_path / "ck") as ck:
        assert ck.save(3, {"w": jnp.zeros(2)})
        ck.wait()
        with pytest.raises(UncorrectableStepError, match="step 3"):
            resilient_step(always_bad, {"w": jnp.ones(2)}, max_retries=0,
                           checkpointer=ck)


def test_no_raise_mode_returns_last_clean_state():
    step, _ = _flaky(10)
    state, metrics, rep = resilient_step(step, 10, max_retries=1,
                                         raise_on_failure=False)
    assert state == 10, "the unverified new_state must never be returned"
    assert metrics is None, "a reporting attempt's metrics are unverified"
    assert isinstance(rep, StepReport) and rep.uncorrectable == 1


def test_pytree_report_is_summed():
    """The report channel accepts pytrees of uncorrectable counts,
    matching the checkpointer's gate."""
    def step(state):
        report = {"layer": {"uncorrectable": jnp.asarray([0, 0])},
                  "bwd_unc": jnp.asarray(0)}
        return state + 1, {}, report

    new_state, _, rep = resilient_step(step, 1)
    assert new_state == 2 and rep.retries == 0


def test_unfiltered_report_tree_is_rejected():
    """A report containing corrected-detection leaves must error loudly —
    treating benign corrected faults as failures would burn every retry."""
    def step(state):
        return state, {}, {"detections": jnp.asarray(4),
                           "uncorrectable": jnp.asarray(0)}

    with pytest.raises(ValueError, match="UNCORRECTABLE counts only"):
        resilient_step(step, 1)


def test_integration_with_ftdense_step():
    """The real step shape from examples/train_ft.py, wrapped: clean run
    (rotating injector, all corrected) → zero retries, state advances."""
    flax = pytest.importorskip("flax")  # noqa: F841
    import optax

    from ft_sgemm_tpu import InjectionSpec
    from ft_sgemm_tpu.configs import KernelShape
    from ft_sgemm_tpu.nn import COUNTS_COLLECTION, FtDense

    tile = KernelShape("t128", 128, 128, 128, (0,) * 7)
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    layer = FtDense(128, shape=tile, inject=inj, inject_bwd=inj)
    x = jax.random.normal(jax.random.key(0), (128, 128)) * 0.3
    y = jnp.roll(x, 1, axis=1)
    params = layer.init(jax.random.key(1), x, jnp.zeros(2))["params"]
    tx = optax.sgd(1e-2)
    state = {"params": params, "opt": tx.init(params)}

    @jax.jit
    def raw_step(state):
        def loss_fn(p, sink):
            out, mut = layer.apply({"params": p}, x, sink,
                                   mutable=[COUNTS_COLLECTION])
            counts = mut[COUNTS_COLLECTION]
            return jnp.mean((out - y) ** 2), counts

        (loss, counts), (g, bwd) = jax.value_and_grad(
            loss_fn, argnums=(0, 1), has_aux=True)(
                state["params"], jnp.zeros(2))
        upd, opt = tx.update(g, state["opt"])
        unc = sum(jnp.sum(v) for p, v in
                  jax.tree_util.tree_leaves_with_path(counts)
                  if "uncorrectable" in str(p)) + bwd[1].astype(jnp.int32)
        new = {"params": optax.apply_updates(state["params"], upd),
               "opt": opt}
        return new, {"loss": loss, "det": counts}, unc

    new_state, metrics, rep = resilient_step(raw_step, state)
    assert rep.retries == 0 and rep.uncorrectable == 0
    assert float(metrics["loss"]) > 0
    changed = jax.tree.map(
        lambda a, b: bool(jnp.any(a != b)),
        state["params"], new_state["params"])
    assert any(jax.tree.leaves(changed))


def test_adversarial_schedule_drives_full_ladder_with_telemetry(tmp_path):
    """End-to-end satellite: the adversarial injection schedule
    (``col_stride=0`` pins every fault to one column, defeating
    per-column localization) drives a REAL uncorrectable report through
    resilient_step's retry -> restore -> raise ladder, and telemetry
    records every stage of it."""
    from ft_sgemm_tpu import InjectionSpec, ft_sgemm, telemetry
    from ft_sgemm_tpu.checkpoint import FtCheckpointer
    from ft_sgemm_tpu.configs import KernelShape
    from ft_sgemm_tpu.telemetry import read_events

    tile = KernelShape("t128", 128, 128, 128, (0,) * 7)
    rng = np.random.default_rng(10)
    a = rng.standard_normal((128, 256)).astype(np.float32)
    b = rng.standard_normal((128, 256)).astype(np.float32)
    c = rng.standard_normal((128, 128)).astype(np.float32)
    # every=1 over nk=2 K-steps: two same-column faults per (single,
    # final) check interval — the case column localization provably
    # cannot correct; the kernel's residual-after-correct re-check must
    # REPORT it.
    adversarial = InjectionSpec(enabled=True, every=1, col_stride=0)

    def step(state):
        res = ft_sgemm(a, b, c, tile, inject=adversarial)
        unc = int(res.num_uncorrectable)
        assert unc > 0, "adversarial schedule must defeat correction"
        return state, {"loss": 0.0}, unc

    log = tmp_path / "ladder.jsonl"
    telemetry.reset()
    try:
        with FtCheckpointer(tmp_path / "ck") as ck:
            assert ck.save(3, {"w": jnp.zeros(2)})
            ck.wait()
            with telemetry.session(log):
                with pytest.raises(UncorrectableStepError,
                                   match="checkpoint step 3"):
                    resilient_step(step, {"w": jnp.ones(2)}, max_retries=2,
                                   checkpointer=ck,
                                   restore_target={"w": jnp.zeros(2)})
    finally:
        telemetry.reset()

    events = list(read_events(log))
    outcomes = [e.outcome for e in events]
    # Every attempt's GEMM recorded its own uncorrectable call event:
    # 3 live attempts + 1 post-restore attempt.
    assert outcomes.count("uncorrectable") == 4
    # The ladder: one retry record per forced re-attempt, then the
    # restore, then the raise — in that order.
    ladder = [o for o in outcomes if o in ("retry", "restore", "raise")]
    assert ladder == ["retry", "retry", "restore", "raise"]
    restore = next(e for e in events if e.outcome == "restore")
    assert restore.extra["restored_step"] == 3
    # Call events carry nonzero uncorrectable counters; ladder records
    # echo the gate total that forced them.
    assert all(e.uncorrectable > 0 for e in events)


def test_gate_total_is_public_with_deprecated_alias():
    from ft_sgemm_tpu import checkpoint

    assert checkpoint._gate_total is checkpoint.gate_total
    assert checkpoint.gate_total({"unc": jnp.asarray(2)}) == 2
    with pytest.raises(ValueError, match="UNCORRECTABLE counts only"):
        checkpoint.gate_total({"detections": 1})


def test_exhausted_outcome_recorded_when_not_raising(tmp_path):
    from ft_sgemm_tpu import telemetry
    from ft_sgemm_tpu.telemetry import read_events

    log = tmp_path / "exhausted.jsonl"
    step, _ = _flaky(10)
    telemetry.reset()
    try:
        with telemetry.session(log):
            state, metrics, rep = resilient_step(
                step, 10, max_retries=1, raise_on_failure=False)
    finally:
        telemetry.reset()
    assert state == 10 and metrics is None and rep.uncorrectable == 1
    # "exhausted" (not a call outcome): the summarizer must not fold its
    # echoed count into the call-counter totals.
    outcomes = [e.outcome for e in read_events(log)]
    assert outcomes == ["retry", "exhausted"]
