"""Transformer-block serving tests (ISSUE 12): ragged prefill/decode
bucketing, the ABFT-checked paged KV cache's corruption semantics
(detection on READ, page-level blame coordinates, in-place correction,
bounded page-scoped restore), the in-flight attention retry ladder, the
clean path's byte-identical HLO with checksums off, ring-path per-device
fault attribution, and the ledger-driven headline resume satellite."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from ft_sgemm_tpu.serve import (
    BlockEngine,
    BlockRequest,
    BucketOverflowError,
    PagedKVCache,
    default_block_bucket_set,
    select_block_bucket,
)
from ft_sgemm_tpu.serve.buckets import BlockBucket

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

D = 16  # head dims small: the kernels pad to their 128-granule tiles


# ---------------------------------------------------------------------------
# Block buckets: the tuner-aligned pow2 rule over sequence dims
# ---------------------------------------------------------------------------


def test_block_bucket_routing_prefill_and_decode():
    buckets = default_block_bucket_set((128, 256, 512), d=D)
    b = select_block_bucket(buckets, 100, "prefill")
    assert (b.lq, b.lk) == (128, 128)
    b = select_block_bucket(buckets, 200, "prefill")
    assert (b.lq, b.lk) == (256, 256)
    # Decode rides the half-lq rungs: the end-anchored causal placement
    # needs len > lk - lq, which the smallest fitting rung satisfies.
    assert select_block_bucket(buckets, 100, "decode").key.startswith(
        "L128xK128")
    b = select_block_bucket(buckets, 200, "decode")
    assert (b.lq, b.lk) == (128, 256)
    b = select_block_bucket(buckets, 400, "decode")
    assert (b.lq, b.lk) == (256, 512)
    with pytest.raises(BucketOverflowError):
        select_block_bucket(buckets, 513, "prefill")


def test_block_bucket_validation():
    with pytest.raises(ValueError, match="power of two"):
        BlockBucket(100, 128, D, D)
    with pytest.raises(ValueError, match="lq"):
        BlockBucket(256, 128, D, D)
    with pytest.raises(ValueError, match="powers of two"):
        default_block_bucket_set((384,), d=D)
    # int8 routes to the exact strategies by the same legality gate the
    # GEMM buckets use.
    b8 = default_block_bucket_set((128,), d=D, in_dtype="int8")
    assert all(b.strategy == "rowcol" for b in b8)


def test_decode_placement_boundary():
    b = BlockBucket(128, 256, D, D)
    assert not b.fits_decode(128)   # len == lk - lq: no valid q row
    assert b.fits_decode(129)
    assert b.fits_decode(256)
    assert not b.fits_decode(257)


# ---------------------------------------------------------------------------
# Paged KV cache: checksum rows, verify-on-read, recovery semantics
# ---------------------------------------------------------------------------


def _cache(rng, rows=20, page_size=8, checksums=True):
    c = PagedKVCache(D, D, page_size=page_size, checksums=checksums)
    k = rng.standard_normal((rows, D)).astype(np.float32)
    v = rng.standard_normal((rows, D)).astype(np.float32)
    c.append(7, 1, 2, k, v)
    return c, k, v


def test_kv_roundtrip_partial_pages(rng):
    c, k, v = _cache(rng, rows=20, page_size=8)  # 2 full + 1 partial
    K, V, faults = c.read(7, 1, 2)
    assert faults == []
    np.testing.assert_array_equal(K, k)
    np.testing.assert_array_equal(V, v)
    assert c.length(7, 1, 2) == 20
    assert c.stats()["verify_hit_rate"] == 1.0


def test_kv_single_element_corruption_corrected_in_place(rng):
    c, k, v = _cache(rng)
    c.corrupt(7, 1, 2, 1, row=3, cols=(5,), magnitude=800.0)
    K, _, faults = c.read(7, 1, 2)
    assert len(faults) == 1
    f = faults[0]
    # Full blame coordinates: stream, page, and the located element.
    assert (f.seq_id, f.layer, f.head, f.page) == (7, 1, 2, 1)
    assert (f.row, f.col) == (3, 5)
    assert f.corrected and f.which == "k"
    np.testing.assert_allclose(K, k, atol=1e-3)
    # The repair is durable: the next read is clean.
    assert c.read(7, 1, 2)[2] == []


def test_kv_corrupted_checksum_row_rebuilt(rng):
    c, k, _ = _cache(rng)
    c.corrupt(7, 1, 2, 0, cols=(2,), magnitude=50.0, target="checksum")
    K, _, faults = c.read(7, 1, 2)
    assert len(faults) == 1 and faults[0].corrected
    np.testing.assert_array_equal(K, k)  # data was never touched
    assert c.stats()["checksum_rows_rebuilt"] == 1
    assert c.read(7, 1, 2)[2] == []


def test_kv_multi_column_corruption_is_uncorrectable_then_restored(rng):
    c, k, v = _cache(rng)
    c.corrupt(7, 1, 2, 0, row=2, cols=(1, 4, 9), magnitude=300.0)
    _, _, faults = c.read(7, 1, 2)
    assert len(faults) == 1 and not faults[0].corrected
    assert faults[0].page == 0
    # The restore arm: rewrite the page from authoritative source rows.
    sl = c.page_slice(0)
    c.restore(7, 1, 2, 0, k[sl], v[sl])
    K, V, faults = c.read(7, 1, 2)
    assert faults == []
    np.testing.assert_array_equal(K, k)
    assert c.stats()["restores"] == 1


def test_kv_append_preserves_existing_corruption(rng):
    """Regression pin: appending to a partially-filled CORRUPTED page
    must not reseal the evidence away — checksum rows update from the
    written rows only, so the next read still detects the earlier hit."""
    c, k, v = _cache(rng, rows=20, page_size=8)  # last page holds 4 rows
    c.corrupt(7, 1, 2, 2, row=1, cols=(3,), magnitude=500.0)
    c.append(7, 1, 2, rng.standard_normal((2, D)).astype(np.float32),
             rng.standard_normal((2, D)).astype(np.float32))
    _, _, faults = c.read(7, 1, 2)
    assert len(faults) == 1
    assert faults[0].page == 2 and faults[0].corrected
    assert (faults[0].row, faults[0].col) == (1, 3)


def test_kv_checksums_off_skips_verification(rng):
    c, k, v = _cache(rng, checksums=False)
    c.corrupt(7, 1, 2, 0, row=0, cols=(0,), magnitude=999.0)
    K, _, faults = c.read(7, 1, 2)
    assert faults == []          # nothing verifies, nothing flags
    assert abs(K[0, 0] - k[0, 0] - 999.0) < 1e-3
    assert c.stats()["pages_verified"] == 0


# ---------------------------------------------------------------------------
# Block engine: prefill/decode dispatch over the checked cache
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def engine(tmp_path_factory):
    """One prewarmed block engine shared by the dispatch tests, its
    timeline streamed for the warm-path pin."""
    tl_path = str(tmp_path_factory.mktemp("blocks")
                  / "blocks.timeline.jsonl")
    eng = BlockEngine(default_block_bucket_set((128, 256), d=D),
                      max_batch=3, max_wait=0.05, retry_backoff=0.001,
                      kv_page_size=16, timeline=tl_path)
    eng.start()
    eng.prewarm()
    yield eng
    eng.close()


def _qkv(rng, n, d=D, dv=D):
    return (rng.standard_normal((n, d)).astype(np.float32),
            rng.standard_normal((n, d)).astype(np.float32),
            rng.standard_normal((n, dv)).astype(np.float32))


def _oracle(q, k, v):
    from ft_sgemm_tpu.ops.attention import attention_reference

    return np.asarray(attention_reference(q, k, v, causal=True))


def test_prefill_matches_causal_oracle_and_stores_pages(engine, rng):
    q, k, v = _qkv(rng, 100)
    req = BlockRequest("prefill", q, k, v)
    res = engine.submit(req).result(timeout=300)
    assert res.ok and res.phase == "prefill" and res.tokens == 100
    np.testing.assert_allclose(res.out, _oracle(q, k, v),
                               rtol=1e-3, atol=1e-3)
    assert engine.kv.length(req.seq_id, 0, 0) == 100
    # Pages sealed: a verified read of the stored stream is clean.
    K, V, faults = engine.kv.read(req.seq_id, 0, 0)
    assert faults == [] and K.shape == (100, D)


def test_decode_extends_sequence_and_matches_oracle(engine, rng):
    q, k, v = _qkv(rng, 60)
    pre = BlockRequest("prefill", q, k, v)
    assert engine.submit(pre).result(timeout=300).ok
    K, V = k, v
    for _ in range(2):
        q1, k1, v1 = _qkv(rng, 1)
        res = engine.submit(
            BlockRequest("decode", q1, k1, v1,
                         seq_id=pre.seq_id)).result(timeout=300)
        K, V = np.vstack([K, k1]), np.vstack([V, v1])
        assert res.ok and res.tokens == 1
        np.testing.assert_allclose(res.out, _oracle(q1, K, V),
                                   rtol=1e-3, atol=1e-3)
    assert engine.kv.length(pre.seq_id, 0, 0) == 62


def test_decode_through_half_lq_bucket(engine, rng):
    """A >128-key prefix routes decode to the (lq=128, lk=256) rung; the
    end-anchored causal placement attends exactly the real keys."""
    q, k, v = _qkv(rng, 150)
    pre = BlockRequest("prefill", q, k, v)
    assert engine.submit(pre).result(timeout=300).ok
    q1, k1, v1 = _qkv(rng, 1)
    res = engine.submit(BlockRequest(
        "decode", q1, k1, v1, seq_id=pre.seq_id)).result(timeout=300)
    assert res.bucket_key.startswith("L128xK256")
    np.testing.assert_allclose(
        res.out, _oracle(q1, np.vstack([k, k1]), np.vstack([v, v1])),
        rtol=1e-3, atol=1e-3)


def test_stored_corruption_detected_on_read_with_blame_and_trace(
        engine, rng, tmp_path):
    """THE stored-state acceptance pin: corruption injected into a page
    BETWEEN decode steps is detected on the next read, blamed on
    (seq, layer, head, page) in a kv_page event carrying the decode
    request's trace_id, corrected in place, and the result verifies."""
    from ft_sgemm_tpu import telemetry

    q, k, v = _qkv(rng, 40)
    pre = BlockRequest("prefill", q, k, v)
    assert engine.submit(pre).result(timeout=300).ok
    engine.corrupt_kv(pre.seq_id, page=1, row=4, cols=(2,),
                      magnitude=700.0)
    log = tmp_path / "kv_events.jsonl"
    telemetry.configure(log, log_clean=True)
    try:
        q1, k1, v1 = _qkv(rng, 1)
        req = BlockRequest("decode", q1, k1, v1, seq_id=pre.seq_id)
        res = engine.submit(req).result(timeout=300)
    finally:
        telemetry.disable()
    assert res.ok and res.kv_faults == 1 and res.kv_corrected == 1
    assert res.corrected  # the stored-state SDC was free
    np.testing.assert_allclose(
        res.out, _oracle(q1, np.vstack([k, k1]), np.vstack([v, v1])),
        rtol=1e-3, atol=1e-3)
    events = [json.loads(line) for line in open(log)]
    kv_events = [e for e in events if e.get("op") == "kv_page"]
    assert len(kv_events) == 1
    ev = kv_events[0]
    assert ev["outcome"] == "corrected"
    assert ev["extra"]["trace_id"] == req.trace_id
    assert (ev["extra"]["seq_id"], ev["extra"]["page"]) == (pre.seq_id, 1)
    assert ev["extra"]["layer"] == 0 and ev["extra"]["head"] == 0
    assert ev["tiles"] == [[1, 4]]
    # ...and the request's own serve_block event joins the same trace.
    blk = [e for e in events if e.get("op") == "serve_block"
           and e.get("extra", {}).get("trace_id") == req.trace_id]
    assert blk and blk[0]["extra"]["block_phase"] == "decode"
    assert blk[0]["extra"]["kv_corrected"] == 1


def test_multi_element_corruption_page_restore_ladder(engine, rng,
                                                      tmp_path):
    """Wider-than-one-element corruption defeats in-place correction:
    the bounded PAGE-scoped restore ladder recovers it — restore event,
    retry ladder record, clean re-verify — never a whole-queue retry."""
    from ft_sgemm_tpu import telemetry

    q, k, v = _qkv(rng, 40)
    pre = BlockRequest("prefill", q, k, v)
    assert engine.submit(pre).result(timeout=300).ok
    engine.corrupt_kv(pre.seq_id, page=0, row=2, cols=(1, 5, 9),
                      magnitude=400.0)
    log = tmp_path / "kv_restore_events.jsonl"
    telemetry.configure(log, log_clean=True)
    try:
        q1, k1, v1 = _qkv(rng, 1)
        req = BlockRequest("decode", q1, k1, v1, seq_id=pre.seq_id)
        res = engine.submit(req).result(timeout=300)
    finally:
        telemetry.disable()
    assert res.ok and res.kv_restores >= 1 and res.kv_ok
    assert res.corrected
    np.testing.assert_allclose(
        res.out, _oracle(q1, np.vstack([k, k1]), np.vstack([v, v1])),
        rtol=1e-3, atol=1e-3)
    events = [json.loads(line) for line in open(log)]
    uncorr = [e for e in events if e.get("op") == "kv_page"
              and e["outcome"] == "uncorrectable"]
    assert uncorr and uncorr[0]["extra"]["trace_id"] == req.trace_id
    ladder = [e for e in events if e.get("op") == "kv_page"
              and e["outcome"] == "retry"]
    assert ladder and ladder[0]["extra"]["page"] == 0
    assert engine.stats()["whole_queue_retries"] == 0


def test_inflight_inject_corrected_free(engine, rng):
    q, k, v = _qkv(rng, 100)
    res = engine.submit(BlockRequest("prefill", q, k, v,
                                     variant="inject")).result(300)
    assert res.ok and res.detections > 0 and res.retries == 0
    assert res.corrected
    np.testing.assert_allclose(res.out, _oracle(q, k, v),
                               rtol=1e-3, atol=1e-3)


def test_adversarial_uses_bucket_scoped_retry(engine, rng):
    """Same-column faults through the PV product's >=2-step K grid are
    uncorrectable in flight: recovered by the bounded bucket-scoped
    retry (clean re-execute), never the whole queue."""
    before = engine.stats()
    q, k, v = _qkv(rng, 200)  # lk 256 bucket: adversarial depth
    res = engine.submit(BlockRequest("prefill", q, k, v,
                                     variant="adversarial")).result(300)
    assert res.ok and res.retries >= 1
    np.testing.assert_allclose(res.out, _oracle(q, k, v),
                               rtol=1e-3, atol=1e-3)
    after = engine.stats()
    assert after["retries"] > before["retries"]
    assert after["whole_queue_retries"] == 0


def test_clean_path_hlo_byte_identical_with_checksums_off():
    """Acceptance pin: the KV checksums are host-side state — disabling
    them changes NOTHING in the compiled executors (byte-identical
    lowered HLO), the same zero-cost-off discipline as telemetry."""
    buckets = default_block_bucket_set((128,), d=D)
    eng_on = BlockEngine(buckets, kv_checksums=True)
    eng_off = BlockEngine(buckets, kv_checksums=False)
    try:
        for variant in ("clean", "inject"):
            on = eng_on.lowered_executor_text(buckets[0], variant)
            off = eng_off.lowered_executor_text(buckets[0], variant)
            assert on == off, f"HLO drift with checksums off ({variant})"
    finally:
        eng_on.close()
        eng_off.close()


def test_prewarmed_steady_state_records_zero_compile_spans(engine):
    """Warm-path purity, block edition: every compile span precedes the
    prewarm_done point; steady-state block serving compiles nothing."""
    from ft_sgemm_tpu.telemetry import timeline as tl_mod

    engine.drain(timeout=30.0)
    records = tl_mod.read_timeline(engine._tl.path)
    done = [r for r in records if r.get("name") == "prewarm_done"]
    assert done, "prewarm_done point missing"
    t_done = done[0]["t"]
    post = [r for r in records if r["t"] > t_done]
    assert not any(r.get("kind") == "compile" for r in post), \
        "steady-state block serving dispatched a compile"
    assert any(r.get("kind") == "stage"
               and str(r.get("name", "")).startswith("serve_block[")
               for r in post)


def test_rejected_overflow_counts(engine, rng):
    before = engine.stats()["rejected"]
    q, k, v = _qkv(rng, 300)  # exceeds the 256 ladder
    with pytest.raises(BucketOverflowError):
        engine.submit(BlockRequest("prefill", q, k, v))
    assert engine.stats()["rejected"] == before + 1


# ---------------------------------------------------------------------------
# Ring path: per-device attribution of in-flight faults (8 vdev CPU)
# ---------------------------------------------------------------------------


def test_ring_inject_attributes_device_and_joins_kv_trace(rng, tmp_path):
    """The 8-vdev acceptance: in-flight attention faults (ring inject,
    pinned to one ring position by inject_coords) AND stored KV-page
    faults are EACH detected and attributed — (request, device) on the
    serve_block event's devices list, (request, page) on the kv_page
    event — all joined by request trace_ids."""
    from ft_sgemm_tpu import telemetry

    eng = BlockEngine(default_block_bucket_set((128,), d=D),
                      max_batch=2, max_wait=0.02, retry_backoff=0.001,
                      kv_page_size=16, ring=True, inject_coords=(2,))
    eng.start()
    log = tmp_path / "ring_events.jsonl"
    telemetry.configure(log, log_clean=True)
    try:
        q, k, v = _qkv(rng, 64)
        pre = BlockRequest("prefill", q, k, v, variant="inject")
        res = eng.submit(pre).result(timeout=300)
        assert res.ok and res.detections > 0
        assert res.devices, "ring inject carried no device blame"
        assert all(d["coords"] == [2] for d in res.devices)
        np.testing.assert_allclose(res.out, _oracle(q, k, v),
                                   rtol=1e-3, atol=1e-3)
        eng.corrupt_kv(pre.seq_id, page=0, row=1, cols=(4,),
                       magnitude=600.0)
        q1, k1, v1 = _qkv(rng, 1)
        dec = BlockRequest("decode", q1, k1, v1, seq_id=pre.seq_id)
        res2 = eng.submit(dec).result(timeout=300)
        assert res2.ok and res2.kv_faults == 1
    finally:
        telemetry.disable()
        eng.close()
    events = [json.loads(line) for line in open(log)]
    ring_ev = [e for e in events if e.get("op") == "serve_block"
               and e.get("devices")]
    assert ring_ev, "no device-attributed serve_block event"
    assert ring_ev[0]["extra"]["trace_id"] == pre.trace_id
    assert ring_ev[0]["devices"][0]["coords"] == [2]
    kv_ev = [e for e in events if e.get("op") == "kv_page"]
    assert kv_ev and kv_ev[0]["extra"]["trace_id"] == dec.trace_id


# ---------------------------------------------------------------------------
# Ledger: serve_block measurements + the headline-resume satellite
# ---------------------------------------------------------------------------


def test_ledger_ingests_block_serve_artifact():
    from ft_sgemm_tpu.perf import ledger

    art = {"metric": "serve_block_goodput_tps", "value": 1200.5,
           "unit": "tokens/s", "vs_baseline": None,
           "context": {"serve": True, "smoke": True, "workload": "block",
                       "goodput_tps": 1200.5, "throughput_tps": 1300.0,
                       "tokens_correct": 640,
                       "p50_latency_seconds": 0.2,
                       "p99_latency_seconds": 0.4,
                       "kv": {"verify_hit_rate": 0.97}}}
    entry = ledger.ingest(art, run_id="blk-1")
    assert entry["kind"] == "serve"
    m = entry["measurements"]
    assert m["serve_block.goodput_tps"] == {
        "value": 1200.5, "higher_is_better": True}
    assert m["serve_block.kv_verify_hit_rate"]["value"] == 0.97
    assert m["serve_block.p99_latency_seconds"]["higher_is_better"] \
        is False
    # Older/gemm rows stay untouched: no serve_block keys, still render.
    gemm = ledger.ingest({"metric": "serve_goodput_rps", "value": 3.0,
                          "unit": "requests/s",
                          "context": {"serve": True}}, run_id="g-1")
    assert not any(k.startswith("serve_block.")
                   for k in gemm["measurements"])
    text = ledger.format_history([entry, gemm])
    assert "blk-1" in text and "g-1" in text


def test_bench_ledger_fresh_values_identity_strict(tmp_path):
    sys.path.insert(0, REPO)
    import bench
    from ft_sgemm_tpu.perf import ledger

    art = {"metric": "abft_kernel_huge_gflops_4096", "value": 4100.0,
           "unit": "GFLOPS", "vs_baseline": 1.02,
           "context": {
               "platform_used": "tpu", "device_kind": "TPU v4",
               "xla_dot_gflops": 5000.0,
               "abft_rowcol_gflops": 3900.0,
               "run_report": {"manifest": {"git_rev": "abc1234"}}}}
    path = str(tmp_path / "ledger.jsonl")
    ledger.append(path, ledger.ingest(art, run_id="BENCH_x"))
    fresh = bench._ledger_fresh_values("abc1234", "tpu", "TPU v4",
                                       ledger_path=path)
    assert fresh["ft_headline"]["value"] == 4100.0
    assert fresh["xla_dot"]["value"] == 5000.0
    assert fresh["ft_rowcol"]["value"] == 3900.0
    assert fresh["ft_headline"]["run_id"] == "BENCH_x"
    # Identity-strict: a different rev, platform, or device kind — or a
    # serve/smoke row — never seeds a resume.
    assert bench._ledger_fresh_values("other000", "tpu", "TPU v4",
                                      ledger_path=path) == {}
    assert bench._ledger_fresh_values("abc1234", "cpu", "TPU v4",
                                      ledger_path=path) == {}
    assert bench._ledger_fresh_values("abc1234", "tpu", "TPU v3",
                                      ledger_path=path) == {}


def test_bench_ledger_resume_stages_wiring(tmp_path, monkeypatch):
    """The worker-side satellite: fresh ledger rungs seed the records
    with the NAMED skipped_fresh_in_ledger reason (records + timeline
    point), and already-done stages are left alone."""
    sys.path.insert(0, REPO)
    import bench
    from ft_sgemm_tpu.perf import ledger

    art = {"metric": "abft_kernel_huge_gflops_4096", "value": 4100.0,
           "unit": "GFLOPS", "vs_baseline": None,
           "context": {
               "platform_used": "tpu", "device_kind": "TPU v4",
               "xla_dot_gflops": 5000.0, "kernel_sgemm_huge_gflops": 4800.0,
               "run_report": {"manifest": {"git_rev": "abc1234"}}}}
    path = str(tmp_path / "ledger.jsonl")
    ledger.append(path, ledger.ingest(art, run_id="BENCH_x"))
    monkeypatch.setenv("FT_SGEMM_LEDGER", path)
    import ft_sgemm_tpu.perf.report as report

    monkeypatch.setattr(report, "_git_rev", lambda *a, **k: "abc1234")

    class Rec:
        def __init__(self):
            self.values = {"xla_dot": 5000.0}

        def done(self, name):
            return name in self.values

        def ok(self, name, value):
            self.values[name] = value

    class TL:
        points = []

        def point(self, kind, name, **fields):
            self.points.append((kind, name, fields))

    rec, tl = Rec(), TL()
    out = bench._ledger_resume_stages(
        rec, tl, {"platform_used": "tpu", "device_kind": "TPU v4"})
    assert sorted(out["stages"]) == ["ft_headline", "plain_huge"]
    assert rec.values["ft_headline"] == {
        "gflops": 4100.0, "strategy": "ledger:BENCH_x"}
    assert rec.values["plain_huge"] == 4800.0
    assert rec.values["xla_dot"] == 5000.0  # already done: untouched
    assert rec.values["ledger_resume"]["reason"] \
        == "skipped_fresh_in_ledger"
    named = [p for p in tl.points
             if p[2].get("note") == "skipped_fresh_in_ledger"]
    assert {p[1] for p in named} == {"ft_headline", "plain_huge"}
    # No match -> no-op.
    rec2 = Rec()
    assert bench._ledger_resume_stages(
        rec2, TL(), {"platform_used": "cpu",
                     "device_kind": "cpu"}) is None


# ---------------------------------------------------------------------------
# bench.py --serve --smoke --workload=block (subprocess acceptance)
# ---------------------------------------------------------------------------


def test_bench_serve_block_smoke_emits_tokens_goodput_artifact(tmp_path):
    """Acceptance: the block smoke on CPU emits ONE non-null JSON line —
    tokens-correct-per-second > 0 under nonzero in-flight injection AND
    stored-page corruption, zero whole-queue retries, zero steady-state
    compile spans, both KV recovery arms exercised, every completed
    request verified correct."""
    tl_path = str(tmp_path / "blk.timeline.jsonl")
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu",
               FT_SGEMM_BENCH_TIMELINE=tl_path,
               FT_SGEMM_TUNER_CACHE=str(tmp_path / "tuner_cache.json"),
               FT_SGEMM_COMPILE_CACHE="0")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--serve",
         "--smoke", "--workload=block"],
        capture_output=True, text=True, timeout=560, env=env, cwd=REPO)
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-2000:])
    line = [l for l in proc.stdout.splitlines() if l.startswith("{")][-1]
    art = json.loads(line)
    assert art["metric"] == "serve_block_goodput_tps"
    assert art["unit"] == "tokens/s"
    assert art["value"] is not None and art["value"] > 0
    ctx = art["context"]
    assert ctx["workload"] == "block"
    assert ctx["goodput_tps"] > 0 and ctx["tokens_correct"] > 0
    assert ctx["whole_queue_retries"] == 0
    assert ctx["uncorrectable_final"] == 0
    assert ctx["correct"] == ctx["completed"] > 0
    assert ctx["verified"] is True
    assert ctx["steady_state_compile_spans"] == 0
    assert ctx["phases"]["decode"] > 0
    assert ctx["kv_corruptions_injected"] > 0
    assert ctx["kv_faults"] > 0
    assert ctx["kv_corrected_in_place"] + ctx["kv_page_restores"] > 0
    assert ctx["p50_latency_seconds"] is not None
    # A kv_page finding joins a decode request by trace_id in the
    # streamed timeline (the stored-state half of the trace join).
    records = [json.loads(l) for l in open(tl_path)]
    kv_traces = {r.get("trace_id") for r in records
                 if r.get("kind") == "kv_page"}
    enq_traces = {r.get("trace_id") for r in records
                  if r.get("kind") == "serve_block"
                  and r.get("name") == "enqueue"}
    assert kv_traces & enq_traces, "no kv_page/request trace join"
