"""Two-pass ABFT baseline tests (reference include/baseline_ft_sgemm.cuh)."""

import numpy as np

from ft_sgemm_tpu import InjectionSpec, abft_baseline_sgemm, sgemm_reference
from ft_sgemm_tpu.ops.reference import cpu_gemm
from ft_sgemm_tpu.utils import generate_random_matrix, verify_matrix

ALPHA, BETA = 1.0, -1.5


def _inputs(m, n, k, seed=10):
    rng = np.random.default_rng(seed)
    a = generate_random_matrix(m, k, rng=rng)
    b = generate_random_matrix(n, k, rng=rng)
    c = generate_random_matrix(m, n, rng=rng)
    return a, b, c


def test_reference_oracle_matches_cpu_gemm():
    a, b, c = _inputs(48, 40, 56)
    got = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    want = cpu_gemm(ALPHA, BETA, a, b.T, c)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_baseline_clean_matches_reference():
    a, b, c = _inputs(128, 96, 512)
    res = abft_baseline_sgemm(a, b, c, ALPHA, BETA)
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok, f"{nbad} elements out of tolerance"
    assert not bool(res.detected)
    # Checksum noise floor is far below the detection threshold.
    assert float(res.max_row_residual) < 1.0
    assert float(res.max_col_residual) < 1.0


def test_baseline_pads_odd_k():
    a, b, c = _inputs(64, 64, 300)  # K not a multiple of the 256 panel
    res = abft_baseline_sgemm(a, b, c, ALPHA, BETA)
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok, f"{nbad} elements out of tolerance"


def test_baseline_detects_injected_fault():
    a, b, c = _inputs(128, 128, 512)
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    res = abft_baseline_sgemm(a, b, c, ALPHA, BETA, inject=inj)
    assert bool(res.detected)
    # Residual magnitude reflects the fault (faults accumulate over panels).
    assert float(res.max_row_residual) > 9500.0
    assert float(res.max_col_residual) > 9500.0


def test_baseline_small_fault_below_threshold_not_detected():
    a, b, c = _inputs(64, 64, 256)
    inj = InjectionSpec(enabled=True, every=1, magnitude=100.0)
    res = abft_baseline_sgemm(a, b, c, ALPHA, BETA, inject=inj)
    # Residual sees the fault but stays below the reference 9500 threshold.
    assert not bool(res.detected)
    assert float(res.max_row_residual) > 50.0


def test_baseline_bf16_clean_and_detects():
    from conftest import bf16_rounded_oracle

    a, b, c = _inputs(128, 96, 512, seed=7)
    res = abft_baseline_sgemm(a, b, c, ALPHA, BETA, in_dtype="bfloat16")
    want = bf16_rounded_oracle(a, b, c, ALPHA, BETA)
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok and not bool(res.detected), f"{nbad} bad"
    # Residual noise stays in the f32 accumulation class (checksums are
    # computed on the rounded inputs), so the reference threshold still works.
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    res2 = abft_baseline_sgemm(a, b, c, ALPHA, BETA, in_dtype="bfloat16",
                               inject=inj)
    assert bool(res2.detected)
