"""Host-utility parity tests (reference utils/utils.cu semantics)."""

import numpy as np

from ft_sgemm_tpu.utils import (
    fill_vector,
    generate_random_matrix,
    generate_random_vector,
    verify_matrix,
    verify_vector,
)


def test_generate_random_matrix_quantized():
    # Values must lie in ±{0, 0.1, ..., 0.9} (utils.cu:23-31) — this keeps
    # checksum noise far below the detection threshold.
    a = generate_random_matrix(64)
    assert a.shape == (64, 64)
    assert a.dtype == np.float32
    scaled = np.round(np.abs(a) * 10)
    assert np.allclose(np.abs(a) * 10, scaled, atol=1e-5)
    assert scaled.max() <= 9
    # Both signs appear.
    assert (a > 0).any() and (a < 0).any()


def test_generate_random_matrix_rectangular_and_seeded():
    a1 = generate_random_matrix(16, 32, seed=3)
    a2 = generate_random_matrix(16, 32, seed=3)
    b = generate_random_matrix(16, 32, seed=4)
    assert a1.shape == (16, 32)
    np.testing.assert_array_equal(a1, a2)
    assert not np.array_equal(a1, b)


def test_generate_random_vector_range():
    v = generate_random_vector(1000)
    assert np.abs(v).max() <= 0.044 + 1e-6  # 4*0.01 + 4*0.001 (utils.cu:15-21)


def test_fill_vector():
    v = fill_vector(1.5, 7)
    assert v.shape == (7,)
    assert (v == np.float32(1.5)).all()


def test_verify_matrix_accepts_within_tolerance():
    ref = np.array([[1.0, 100.0], [0.001, -5.0]], dtype=np.float32)
    # abs err <= 0.01 passes even at big relative error (utils.cu:70: needs
    # BOTH abs > 0.01 AND rel > 0.01 to fail).
    out = ref + np.float32(0.009)
    ok, nbad, first = verify_matrix(ref, out)
    assert ok and nbad == 0 and first is None


def test_verify_matrix_rejects_large_error():
    ref = np.ones((4, 4), dtype=np.float32)
    out = ref.copy()
    out[2, 3] = 1.5
    ok, nbad, first = verify_matrix(ref, out, verbose=False)
    assert not ok
    assert nbad == 1
    assert first == (2, 3)


def test_verify_matrix_relative_only_error_passes():
    # Large relative error on a large value -> abs dominates -> fails;
    # large relative error on a tiny value with abs <= 0.01 -> passes.
    ref = np.full((2, 2), 0.0001, dtype=np.float32)
    out = ref * 50  # abs err ~0.0049 < 0.01
    ok, _, _ = verify_matrix(ref, out)
    assert ok


def test_verify_vector():
    ref = np.array([1.0, 2.0, 3.0], dtype=np.float32)
    ok, nbad = verify_vector(ref, ref + 0.001)
    assert ok and nbad == 0
    bad = ref.copy()
    bad[1] = 2.5
    ok, nbad = verify_vector(ref, bad)
    assert not ok and nbad == 1


def test_bench_seconds_per_call_times_real_work():
    # The barrier-chained rep loop must (a) return a positive per-call time
    # and (b) reflect the result of real executions — the loop's carry reads
    # an output element, so a broken chain (hoisted/elided call) would still
    # produce a value, hence the separate correctness check below.
    import jax.numpy as jnp

    from ft_sgemm_tpu.utils.timing import bench_seconds_per_call

    calls = []

    def fn(a, b, c):
        calls.append(1)  # trace-time only: counts compilations, not reps
        return jnp.dot(a, b.T, preferred_element_type=jnp.float32) - 1.5 * c

    a = jnp.ones((64, 64), jnp.float32)
    b = jnp.ones((64, 64), jnp.float32)
    c = jnp.ones((64, 64), jnp.float32)
    sec = bench_seconds_per_call(fn, a, b, c, min_device_time=0.01)
    assert sec > 0
    assert len(calls) >= 1


def test_compile_bench_loop_is_aot_only_and_warms_the_timed_path():
    """compile_bench_loop must build the timing loop's exact executable
    from abstract ShapeDtypeStructs — operands with no data, so any
    device execution of the lowered computation would raise — and the
    shared constructor means the timed path traces byte-identical HLO
    (the cache-warming contract of scripts/compile_probe.py)."""
    import jax
    import jax.numpy as jnp

    from ft_sgemm_tpu.utils import timing

    def fn(a, b, c):
        return a @ b.T + c

    sds = jax.ShapeDtypeStruct((256, 256), jnp.float32)
    timing.compile_bench_loop(fn, sds, sds, sds)  # must not raise

    lowered_probe = timing._make_rep_loop(fn).lower(
        sds, sds, sds, timing.NUM_TESTS, jnp.float32(0))
    lowered_timed = timing._make_rep_loop(fn).lower(
        sds, sds, sds, 5, jnp.float32(0))
    assert (lowered_probe.as_text() == lowered_timed.as_text()), (
        "probe and timed-path HLO diverged: probe compiles would no "
        "longer warm the persistent cache for bench")
