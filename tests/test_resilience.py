"""PR-15 acceptance pins: elastic recovery.

- staged (ICI-first) data-plane checksum reduction equals the flat sum
  within f32 tolerance, and each corruption shape is detected at its
  cheapest visible tier (device / host / global) on the 8-vdev mesh;
- the recompute ladder picks the cheapest sufficient rung under single-
  and multi-element corruption, never skips a cheaper rung that would
  have sufficed (oracle-checked), and a panel recompute costs
  ~1/num_panels of the full retry (the pinned flops ratio);
- the 8-vdev eviction fire drill: persistent faults on one device under
  live load -> EVICTED (not just drained) -> queued batches migrate ->
  goodput recovers with zero lost/incorrect responses, MTTR + tier
  counts in the artifact and ingestable into the ledger;
- ``train.resilient_step`` gains the eviction hook (rebuild on the
  surviving mesh, one recovery attempt, ``report.evicted``);
- ``BlockEngine(pool=)`` serves transformer blocks through the device
  pool with per-device replicas and zero steady-state compiles.
"""

import jax
import numpy as np
import pytest

from ft_sgemm_tpu.configs import KernelShape
from ft_sgemm_tpu.contracts import LADDER_RUNGS as CONTRACT_RUNGS
from ft_sgemm_tpu.contracts import RECOVERY_TIERS as CONTRACT_TIERS
from ft_sgemm_tpu.parallel.sharded import make_mesh
from ft_sgemm_tpu.resilience import (
    ElasticController,
    EvictionPolicy,
    run_eviction_drill,
    surviving_mesh,
)
from ft_sgemm_tpu.resilience.recompute import (
    LADDER_RUNGS,
    encode_expected,
    panel_bounds,
    recover_local,
)
from ft_sgemm_tpu.resilience.tiers import (
    TIERS,
    checksum_tolerance,
    detect_tiers,
    staged_reduce_np,
    tiered_ft_sgemm,
    verify_resident,
)
from ft_sgemm_tpu.telemetry.events import AXIS_LABELS
from ft_sgemm_tpu.telemetry.registry import MetricsRegistry
from ft_sgemm_tpu.utils.matrices import generate_random_matrix

TILE = KernelShape("t128", 128, 128, 128, (0,) * 7)


def _mesh_operands(mesh, m=256, n=128, k=512, seed=10):
    rng = np.random.default_rng(seed)
    return (generate_random_matrix(m, k, rng=rng),
            generate_random_matrix(n, k, rng=rng),
            generate_random_matrix(m, n, rng=rng))


# --- contracts mirrors --------------------------------------------------


def test_recovery_axes_mirror_contracts():
    assert TIERS == CONTRACT_TIERS
    assert LADDER_RUNGS == CONTRACT_RUNGS
    assert AXIS_LABELS["recovery_tier"] == CONTRACT_TIERS
    assert AXIS_LABELS["ladder_rung"] == CONTRACT_RUNGS


# --- checksum tiers -----------------------------------------------------


def test_staged_reduce_equals_flat_f32_tolerance(rng):
    # The staged (axis-at-a-time) reduction of the per-device residual
    # grids equals the flat sum up to f32 reassociation — the float
    # analog of the PR-14 exact counter pin, tolerance-aware because
    # checksum vectors reassociate where int32 counters cannot.
    grid = rng.standard_normal((2, 4, 128)).astype(np.float32)
    stages = staged_reduce_np(grid, (1, 0))
    flat = grid.astype(np.float64).sum(axis=(0, 1))
    staged = stages[-1].reshape(128)
    np.testing.assert_allclose(staged, flat, rtol=1e-6,
                               atol=1e-5 * np.abs(flat).max())
    # And the in-mesh staging agrees with the host mirror: a clean
    # tiered GEMM's global-stage vectors are the summed device vectors.
    mesh = make_mesh(8)
    a, b, c = _mesh_operands(mesh)
    _, report = tiered_ft_sgemm(a, b, c, mesh, TILE,
                                registry=MetricsRegistry())
    assert not report.detected
    # clean noise sits far below every tier tolerance
    for tier in TIERS:
        assert report.residuals[tier] < 0.1 * report.tolerances[tier]


def test_tier_of_detection_device_host_global(rng):
    mesh = make_mesh(8)
    mx, my = mesh.shape["x"], mesh.shape["y"]
    a, b, c = _mesh_operands(mesh)
    tol0 = checksum_tolerance(256 // mx, 512 // my,
                              float(np.abs(a).max()),
                              float(np.abs(b).max()))
    reg = MetricsRegistry()

    # One unmistakably-local corruption -> the (cheapest) device tier,
    # blamed on the right device and column.
    _, rep = tiered_ft_sgemm(
        a, b, c, mesh, TILE, registry=reg,
        tier_corrupt=(((1, 2), (1, 3), 50.0 * tol0),))
    assert rep.detected and rep.tier == "device"
    assert rep.device_coords == (1, 2)
    assert rep.columns == [3]

    # Sibling accumulation: each y-device of one row below tol0, the
    # first staged (ICI) reduce crosses sqrt(Y) x tol0 -> host tier.
    _, rep = tiered_ft_sgemm(
        a, b, c, mesh, TILE, registry=reg,
        tier_corrupt=tuple(((0, y), (1, 3), 0.9 * tol0)
                           for y in range(my)))
    assert rep.detected and rep.tier == "host"

    # Mesh-wide drift: every device AND every ICI row sub-threshold,
    # only the full reduction sees it -> global tier.
    _, rep = tiered_ft_sgemm(
        a, b, c, mesh, TILE, registry=reg,
        tier_corrupt=tuple(((x, y), (1, 3), 0.9 * tol0 / np.sqrt(my))
                           for x in range(mx) for y in range(my)))
    assert rep.detected and rep.tier == "global"

    # Tier-of-detection lands in the registry, labeled per tier.
    counts = {}
    for series in reg.collect():
        if series["name"] == "recovery_tier_detections":
            counts[series["labels"]["recovery_tier"]] = series["value"]
    assert counts == {"device": 1, "host": 1, "global": 1}


def test_tiered_clean_output_matches_sharded(rng):
    # The tier emission must not perturb the computation: outputs match
    # the plain sharded path's oracle.
    from ft_sgemm_tpu.ops.reference import sgemm_reference
    from ft_sgemm_tpu.utils.matrices import verify_matrix

    mesh = make_mesh(8)
    a, b, c = _mesh_operands(mesh, seed=3)
    res, rep = tiered_ft_sgemm(a, b, c, mesh, TILE, alpha=1.0,
                               beta=-1.5, registry=MetricsRegistry())
    want = np.asarray(sgemm_reference(a, b, c, 1.0, -1.5))
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok, f"{nbad} bad"
    assert not rep.detected


def test_verify_resident_detects_and_localizes(rng):
    a = rng.standard_normal((64, 64)).astype(np.float32)
    b = rng.standard_normal((96, 64)).astype(np.float32)
    c = a @ b.T
    assert not verify_resident(a, b, c).detected
    c_bad = c.copy()
    c_bad[5, 17] += 500.0
    rep = verify_resident(a, b, c_bad)
    assert rep.detected and rep.tier == "device"
    assert rep.columns == [17]


def test_detect_tiers_cancellation_visible_only_below():
    # +d / -d on two devices of different ICI rows cancel at the global
    # tier — the device tier still convicts both. The hierarchy is not
    # redundant: lower tiers see faults upper tiers cannot.
    grid = np.zeros((2, 4, 8), np.float32)
    grid[0, 0, 3] = 5.0
    grid[1, 0, 3] = -5.0
    rep = detect_tiers(grid, 1.0, tier_axes=(1, 0))
    assert rep.detected and rep.tier == "device"
    assert rep.residuals["global"] < rep.tolerances["global"]


# --- recompute ladder ---------------------------------------------------


@pytest.fixture
def ladder_problem(rng):
    m, n, k = 64, 256, 64
    a = rng.standard_normal((m, k)).astype(np.float32)
    b = rng.standard_normal((n, k)).astype(np.float32)
    return a, b, a @ b.T


def test_ladder_single_element_cheapest_rung(ladder_problem):
    a, b, clean = ladder_problem
    bad = clean.copy()
    bad[3, 7] += 1000.0
    fixed, o = recover_local(a, b, bad)
    assert o.rung == "element_correct"
    assert o.attempted == ("element_correct",)
    assert o.element == (3, 7)
    assert o.corrected
    np.testing.assert_allclose(fixed, clean, atol=1e-3)
    # O(m+n) work: four-plus orders below a full recompute here.
    assert o.flops_ratio < 1e-3


def test_ladder_multi_element_panel_recompute_flops_pinned(
        ladder_problem):
    a, b, clean = ladder_problem
    bad = clean.copy()
    bad[3, 7] += 1000.0
    bad[9, 9] -= 750.0  # two elements, same 32-wide panel
    fixed, o = recover_local(a, b, bad, num_panels=8)
    assert o.rung == "panel_recompute"
    assert o.panels == [0]
    assert o.corrected
    np.testing.assert_allclose(fixed, clean, atol=1e-3)
    # The acceptance pin: a panel recompute costs ~1/num_panels of the
    # full retry it replaces (exactly 1/8 here; 1.5x slack for the
    # remainder-absorbing last panel in general).
    assert o.flops_ratio <= 1.5 / 8
    assert o.recomputed_flops < o.full_retry_flops / 4


def test_ladder_never_skips_sufficient_cheaper_rung(ladder_problem):
    # Oracle check of "never skips a cheaper rung that would have
    # sufficed": for every scenario, the chosen rung's cheaper
    # neighbors either had a provably-unsatisfiable precondition or
    # were attempted and failed re-verification.
    a, b, clean = ladder_problem
    # (a) single element -> element_correct chosen; nothing cheaper.
    bad = clean.copy()
    bad[3, 7] += 1000.0
    _, o = recover_local(a, b, bad)
    assert o.attempted[0] == LADDER_RUNGS[0]
    # (b) two bad rows x one bad column: element precondition (exactly
    # one of each) is provably unsatisfiable -> panel rung is the
    # cheapest that can suffice, and it does.
    bad = clean.copy()
    bad[3, 7] += 1000.0
    bad[9, 7] += 800.0
    _, o = recover_local(a, b, bad)
    assert o.rung == "panel_recompute"
    assert "element_correct" not in o.attempted
    # (c) corruption spread over EVERY panel: panel rung cannot beat a
    # shard restore (precondition fails), ladder escalates, output
    # still exact.
    bad = clean.copy()
    for j in range(0, 256, 32):
        bad[5, j] += 500.0
    fixed, o = recover_local(a, b, bad, num_panels=8)
    assert o.rung == "shard_restore"
    np.testing.assert_allclose(fixed, clean, atol=1e-3)
    # (d) ambiguous localization (multi-element, one panel): the ladder
    # must TRY the panel rung (cheaper) before any escalation.
    bad = clean.copy()
    bad[3, 7] += 1000.0
    bad[9, 9] -= 750.0
    _, o = recover_local(a, b, bad)
    assert o.attempted == ("panel_recompute",)


def test_ladder_full_retry_when_residents_corrupt(ladder_problem):
    # Encode-time expectations convict a corrupted resident operand:
    # every local rung recomputes from the corrupted A and fails
    # re-verification -> terminal full_retry, corrected=False.
    a, b, clean = ladder_problem
    expected = encode_expected(a, b)
    a_bad = a.copy()
    a_bad[0, 0] += 100.0
    bad = clean.copy()
    for j in range(0, 256, 32):
        bad[5, j] += 500.0
    _, o = recover_local(a_bad, b, bad, expected=expected)
    assert o.rung == "full_retry"
    assert not o.corrected
    assert o.attempted[-1] == "full_retry"
    assert o.recomputed_flops > o.full_retry_flops  # spent + priced


def test_panel_bounds_cover_exactly():
    for n, p in ((256, 8), (100, 8), (7, 16), (128, 1)):
        bounds = panel_bounds(n, p)
        assert bounds[0][0] == 0 and bounds[-1][1] == n
        for (lo, hi), (lo2, _hi2) in zip(bounds, bounds[1:]):
            assert hi == lo2 and hi > lo


# --- pool eviction semantics -------------------------------------------


def test_pool_evict_stronger_than_drain():
    from ft_sgemm_tpu.serve import DevicePool

    pool = DevicePool(jax.local_devices()[:4])
    # Drain: sick device out of eligible but still listed, queue kept.
    pool.mark_sick(1)
    assert 1 not in pool.eligible()
    pool.put(1, "queued-item")
    leftovers = pool.evict(1)
    assert leftovers == ["queued-item"]
    assert pool.evicted == frozenset({1})
    assert 1 not in pool.eligible()
    assert pool.queue_depth(1) == 0
    # Idempotent; stats name it.
    assert pool.evict(1) == []
    assert pool.stats()["evicted"] == [pool.labels[1]]
    # Even when EVERY device is below the drain floor, an evicted
    # device is never re-admitted (drain's degraded-service fallback
    # stops at eviction).
    for i in (0, 2, 3):
        pool.mark_sick(i)
    assert 1 not in pool.eligible()
    # Refuses to evict the last live device.
    pool.evict(0)
    pool.evict(2)
    with pytest.raises(RuntimeError):
        pool.evict(3)


def test_pool_round_robin_skips_evicted():
    from ft_sgemm_tpu.serve import DevicePool

    pool = DevicePool(jax.local_devices()[:3], placement="round_robin",
                      health=None)
    pool.evict(1)
    picks = [pool.choose() for _ in range(4)]
    assert 1 not in picks
    assert set(picks) == {0, 2}


def test_engine_evict_migrates_queued_batches(rng):
    # Deterministic migration pin: batches queued on the victim BEFORE
    # workers start are re-placed on survivors by evict_device and then
    # complete correctly once the engine runs.
    import time as _time

    from ft_sgemm_tpu.ops.reference import sgemm_reference
    from ft_sgemm_tpu.serve import DevicePool, ServeEngine, ServeRequest
    from ft_sgemm_tpu.serve.engine import _Entry, _Future

    pool = DevicePool(jax.local_devices()[:3], max_in_flight=1)
    engine = ServeEngine(_mini_buckets(), max_batch=1,
                         registry=MetricsRegistry(), pool=pool)
    engine.prewarm()
    bucket = engine.buckets[0]
    entries = []
    for _ in range(3):
        a = rng.standard_normal((96, 100)).astype(np.float32)
        b = rng.standard_normal((120, 100)).astype(np.float32)
        req = ServeRequest(a=a, b=b)
        entries.append(_Entry(req, _Future(), _time.monotonic()))
    for e in entries:
        pool.put(1, (bucket, [e]))
    with engine._cond:
        engine._outstanding += len(entries)
    facts = engine.evict_device(1, reason="manual")
    assert facts["migrated"] == 3
    assert pool.queue_depth(1) == 0
    # Migrated batches landed on surviving queues, not the victim's.
    assert (pool.queue_depth(0) + pool.queue_depth(2)) == 3
    engine.start()
    results = [e.future.result(timeout=120) for e in entries]
    engine.close()
    for e, r in zip(entries, results):
        assert r.ok
        want = np.asarray(sgemm_reference(
            e.request.a, e.request.b, np.zeros((96, 120), np.float32),
            1.0, 0.0))
        np.testing.assert_allclose(r.c, want, rtol=2e-4, atol=2e-3)
    # Eviction facts reached the registry under the device label.
    names = {(s["name"], s["labels"].get("device"))
             for s in engine.registry.collect()}
    assert ("recovery_evictions", pool.labels[1]) in names


def _mini_buckets():
    from ft_sgemm_tpu.serve import default_bucket_set

    return default_bucket_set((128,))


def test_elastic_controller_policy():
    from ft_sgemm_tpu.serve import DevicePool
    from ft_sgemm_tpu.telemetry.monitor import DeviceHealthTracker

    health = DeviceHealthTracker()
    pool = DevicePool(jax.local_devices()[:4], health=health)
    ctl = ElasticController(EvictionPolicy(min_calls=8))
    assert ctl.should_evict(pool) is None
    # Evidence below the calls floor: no eviction yet.
    health.observe(pool.labels[2], calls=4, detected=4, uncorrectable=4)
    assert ctl.should_evict(pool) is None
    health.observe(pool.labels[2], calls=8, detected=8, uncorrectable=8)
    decision = ctl.should_evict(pool)
    assert decision == (2, "health_floor")
    # Handed out once: a second ask (pre-record) proposes nothing.
    assert ctl.should_evict(pool) is None
    ctl.record_eviction({"index": 2, "device": pool.labels[2]})
    assert len(ctl.evictions) == 1
    # Panel-recompute blame path.
    ctl2 = ElasticController(EvictionPolicy(panel_recompute_limit=2))
    pool2 = DevicePool(jax.local_devices()[:2], health=None,
                       placement="round_robin")
    ctl2.note_panel_recompute(pool2.labels[1])
    assert ctl2.should_evict(pool2) is None
    ctl2.note_panel_recompute(pool2.labels[1])
    assert ctl2.should_evict(pool2) == (1, "panel_recompute")


def test_surviving_mesh_power_of_two():
    devs = jax.local_devices()
    mesh = surviving_mesh(devs[1], devices=devs)
    # 8 devices minus 1 -> largest pow2 is 4, most-square split 2x2.
    assert mesh.shape["x"] * mesh.shape["y"] == 4
    assert str(devs[1]) not in {str(d) for d in mesh.devices.flat}
    with pytest.raises(ValueError):
        surviving_mesh(list(range(3)), devices=devs[:3])


# --- the 8-vdev eviction fire drill ------------------------------------


def test_eviction_drill_end_to_end(rng):
    stats = run_eviction_drill(smoke=True, registry=MetricsRegistry())
    rec = stats["recovery"]
    # Evicted — not just drained — under live traffic.
    assert rec["evictions"] == 1
    assert rec["evicted_device"] == stats["evict_device"]
    assert stats["pool"]["evicted"] == [stats["evict_device"]]
    assert rec["reason"] == "health_floor"
    # The device was serving before the fault and NEVER after eviction.
    assert rec["pre_fault_target_batches"] > 0
    assert rec["post_eviction_batches_on_evicted"] == 0
    # Zero lost or incorrect responses across all three phases.
    assert stats["completed"] == stats["requests_submitted"]
    assert rec["incorrect_responses"] == 0
    # Goodput recovered on the survivors; MTTR measured.
    assert rec["goodput_recovery_ratio"] is not None
    assert rec["goodput_recovery_ratio"] > 0.7
    assert rec["mttr_seconds"] is not None and rec["mttr_seconds"] >= 0
    # The whole recovery machinery rehearsed into the same artifact.
    assert rec["tier_detections"] == {"device": 1, "host": 1,
                                      "global": 1}
    assert rec["ladder"] == {"element_correct": 1, "panel_recompute": 1}
    assert rec["panel_recompute_flops_ratio"] == pytest.approx(
        1 / 8, rel=0.5)
    assert stats["ok"]
    _drill_stats_cache.append(stats)


# The drill is the expensive fixture of this file: later tests reuse its
# stats instead of re-running three serve phases.
_drill_stats_cache: list = []


def test_drill_recovery_lands_in_ledger(tmp_path):
    from ft_sgemm_tpu.perf import ledger

    stats = (_drill_stats_cache[0] if _drill_stats_cache
             else {"recovery": {
                 "mttr_seconds": 0.2, "evictions": 1,
                 "panel_recompute_flops_ratio": 0.125,
                 "goodput_recovery_ratio": 1.1,
                 "evicted_device": "cpu:1", "reason": "health_floor",
                 "tier_detections": {"device": 1}},
                 "goodput_rps": 10.0})
    artifact = {"metric": "serve_goodput_rps",
                "value": stats.get("goodput_rps"),
                "unit": "requests/s",
                "context": dict(stats, serve=True, drill=True)}
    entry = ledger.ingest(artifact, run_id="drill_test")
    ms = entry["measurements"]
    rec = stats["recovery"]
    assert ms["recovery.mttr_seconds"]["value"] == pytest.approx(
        rec["mttr_seconds"])
    assert ms["recovery.mttr_seconds"]["higher_is_better"] is False
    assert ms["recovery.evictions"]["value"] == 1.0
    assert ms["recovery.panel_recompute_flops_ratio"][
        "higher_is_better"] is False
    assert ms["recovery.goodput_recovery_ratio"][
        "higher_is_better"] is True
    assert entry["recovery"]["evicted_device"] == rec["evicted_device"]
    assert entry["recovery"]["tier_detections"] == \
        rec["tier_detections"]
    # Round-trips through the ledger file like any other row.
    path = tmp_path / "ledger.jsonl"
    ledger.append(str(path), entry)
    rows = ledger.read_ledger(str(path))
    assert rows[-1]["measurements"]["recovery.mttr_seconds"] == \
        ms["recovery.mttr_seconds"]


# --- train.resilient_step eviction hook --------------------------------


def test_resilient_step_eviction_hook_recovers():
    from ft_sgemm_tpu.train import resilient_step

    calls = {"sick": 0, "rebuilt": 0, "hook": 0}

    def sick_step(state):
        calls["sick"] += 1
        return state + 1, {"loss": 1.0}, 3  # persistent report

    def rebuilt_step(state):
        calls["rebuilt"] += 1
        return state + 1, {"loss": 1.0}, 0  # survivors run clean

    def on_persistent_fault(attempts, unc):
        calls["hook"] += 1
        assert attempts == 3 and int(unc) == 3
        # A real hook evicts + rebuilds on surviving_mesh(); the
        # contract under test is the ladder position and the rebuilt
        # step's adoption.
        return rebuilt_step

    state, metrics, report = resilient_step(
        sick_step, 0, max_retries=2,
        on_persistent_fault=on_persistent_fault)
    assert calls == {"sick": 3, "rebuilt": 1, "hook": 1}
    assert state == 1 and metrics == {"loss": 1.0}
    assert report.evicted
    assert report.retries == 3
    assert report.restored_step is None


def test_resilient_step_hook_declines_then_ladder_continues():
    from ft_sgemm_tpu.train import UncorrectableStepError, resilient_step

    def sick_step(state):
        return state + 1, None, 1

    # Hook declines (returns None): the historical raise path stands.
    with pytest.raises(UncorrectableStepError):
        resilient_step(sick_step, 0, max_retries=1,
                       on_persistent_fault=lambda a, u: None)


# --- BlockEngine(pool=) smoke ------------------------------------------


def test_block_engine_pool_smoke(rng):
    from ft_sgemm_tpu.serve import (
        BlockEngine,
        BlockRequest,
        DevicePool,
        default_block_bucket_set,
    )
    from ft_sgemm_tpu.serve.blocks import new_sequence_id

    pool = DevicePool(jax.local_devices()[:4], max_in_flight=1)
    buckets = default_block_bucket_set((128,), d=64)
    with BlockEngine(buckets, max_batch=1, registry=MetricsRegistry(),
                     pool=pool) as engine:
        engine.prewarm()
        compiled_after_prewarm = len(engine._compiled)
        # Per-device replicas: every (bucket, variant) compiled once
        # per pool device.
        assert compiled_after_prewarm == len(buckets) * 3 * 4
        futs = []
        reqs = []
        for _ in range(6):
            L = int(rng.integers(48, 96))
            q = rng.standard_normal((L, 64)).astype(np.float32)
            k = rng.standard_normal((L, 64)).astype(np.float32)
            v = rng.standard_normal((L, 64)).astype(np.float32)
            req = BlockRequest("prefill", q, k, v,
                               seq_id=new_sequence_id())
            reqs.append(req)
            futs.append(engine.submit(req))
        engine.drain(timeout=300)
        results = [f.result(timeout=300) for f in futs]
        # Zero steady-state compiles pool-wide.
        assert len(engine._compiled) == compiled_after_prewarm
        stats = engine.stats()
    assert all(r.ok for r in results)
    # Oracle correctness through the pool path (causal attention).
    from ft_sgemm_tpu.ops.attention import attention_reference

    for req, res in zip(reqs, results):
        want = np.asarray(attention_reference(req.q, req.k, req.v,
                                              causal=True))
        np.testing.assert_allclose(res.out, want, rtol=2e-4, atol=2e-3)
    assert stats["pool"]["devices_used"] > 1
    assert stats["ring"] is False


def test_block_engine_pool_refuses_ring():
    from ft_sgemm_tpu.serve import (
        BlockEngine,
        DevicePool,
        default_block_bucket_set,
    )

    with pytest.raises(ValueError, match="ring"):
        BlockEngine(default_block_bucket_set((128,), d=64),
                    pool=DevicePool(jax.local_devices()[:2]), ring=True)
