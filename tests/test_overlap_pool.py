"""PR-14 acceptance pins: overlap-pipelined ring collectives, staged
(hierarchical) counter reduction, and the health-steered multi-device
serve pool.

- the rotate-ahead ring schedule is byte-value identical to the serial
  one — outputs AND per-device counters, FT/plain/attention, with
  ``inject_coords=`` attribution intact;
- the staged counter reduction (``parallel/reduce.py``) equals the flat
  psum exactly on the 8-vdev meshes;
- the ``ring_overlap`` tuner axis round-trips: schema-5 key, schema-4
  files miss cleanly with the standard warning, ``tune_ring`` winners
  serve ``ring_overlap=None`` dispatch;
- the device pool places over >1 device, drains a marked-sick device
  while results stay correct, and compiles nothing after prewarm;
- the bench emits a platform-honest CPU smoke headline (non-null value)
  when no TPU exists — the BENCH_r06 contract;
- a multichip wrapper carrying real measurements ingests with them
  (the MULTICHIP_r06 contract) while the legacy ok-flag probe keeps its
  named degradation.
"""

import importlib.util
import json
import os
import pathlib
import subprocess
import sys
import warnings

import jax
import numpy as np
import pytest

from ft_sgemm_tpu.configs import KernelShape, KernelVariant
from ft_sgemm_tpu.injection import InjectionSpec
from ft_sgemm_tpu.parallel.reduce import hierarchical_psum
from ft_sgemm_tpu.parallel.ring import (
    make_ring_ft_sgemm_fn,
    make_ring_mesh,
    ring_ft_sgemm,
    ring_sgemm,
)
from ft_sgemm_tpu.parallel.ring_attention import ring_ft_attention

TILE = KernelShape("t128", 128, 128, 128, (0,) * 7)
INJ = InjectionSpec(enabled=True, every=1, magnitude=10000.0)


def _operands(rng, m=256, n=256, k=256):
    return (rng.standard_normal((m, k)).astype(np.float32),
            rng.standard_normal((n, k)).astype(np.float32),
            rng.standard_normal((m, n)).astype(np.float32))


# --- overlap schedule: byte-value equivalence ---------------------------


def test_ring_ft_overlap_byte_equal_with_device_counters(rng):
    mesh = make_ring_mesh(8)
    a, b, c = _operands(rng)
    outs = {}
    for mode in ("serial", "overlap"):
        fn = make_ring_ft_sgemm_fn(
            mesh, 8, 32, 256, TILE, alpha=1.0, beta=-1.5, inject=INJ,
            strategy="weighted", threshold="static", precision="highest",
            in_dtype="float32", interpret=None, inject_coords=(3,),
            overlap=mode == "overlap")
        out, det, unc, dev_det, dev_unc = jax.jit(fn)(a, b, c)
        outs[mode] = (np.asarray(out), np.asarray(det),
                      np.asarray(dev_det), np.asarray(dev_unc))
    out_s, det_s, dd_s, du_s = outs["serial"]
    out_o, det_o, dd_o, du_o = outs["overlap"]
    assert np.array_equal(out_s, out_o)  # byte-value, not allclose
    assert np.array_equal(det_s, det_o)
    # Per-device attribution survives the schedule change: only ring
    # position 3 injected, under BOTH schedules, identically.
    assert np.array_equal(dd_s, dd_o)
    assert np.array_equal(du_s, du_o)
    assert dd_s[3] > 0
    assert all(dd_s[i] == 0 for i in range(8) if i != 3)
    assert int(det_s.sum()) == int(dd_s.sum())


def test_ring_plain_overlap_byte_equal(rng):
    mesh = make_ring_mesh(8)
    a, b, c = _operands(rng)
    got = {mode: np.asarray(ring_sgemm(a, b, c, mesh, TILE,
                                       ring_overlap=mode))
           for mode in ("serial", "overlap")}
    assert np.array_equal(got["serial"], got["overlap"])


def test_ring_attention_overlap_byte_equal(rng):
    mesh = make_ring_mesh(8)
    q = rng.standard_normal((256, 128)).astype(np.float32)
    k = rng.standard_normal((256, 128)).astype(np.float32)
    v = rng.standard_normal((256, 128)).astype(np.float32)
    res = {}
    for mode in ("serial", "overlap"):
        r = ring_ft_attention(q, k, v, mesh, causal=True, inject=INJ,
                              inject_coords=(2,), ring_overlap=mode)
        res[mode] = (np.asarray(r.out), int(r.detections),
                     int(r.softmax_flags), int(r.uncorrectable))
    assert np.array_equal(res["serial"][0], res["overlap"][0])
    assert res["serial"][1:] == res["overlap"][1:]
    assert res["serial"][1] > 0  # injection really ran


def test_ring_overlap_rejects_unknown_mode(rng):
    mesh = make_ring_mesh(8)
    a, b, c = _operands(rng)
    with pytest.raises(ValueError, match="ring_overlap"):
        ring_ft_sgemm(a, b, c, mesh, TILE, ring_overlap="bogus")


# --- hierarchical counter reduction -------------------------------------


def test_hierarchical_psum_equals_flat_on_3_axis_mesh(rng):
    from jax.sharding import Mesh, PartitionSpec as P

    from ft_sgemm_tpu.parallel.sharded import shard_map

    devs = np.asarray(jax.devices()[:8]).reshape(2, 2, 2)
    mesh = Mesh(devs, ("host", "x", "y"))
    vals = rng.integers(0, 100, size=(8, 4)).astype(np.int32)

    def step(x):
        staged = hierarchical_psum(x, ("y", "x", "host"))
        flat = jax.lax.psum(x, ("y", "x", "host"))
        return staged, flat

    fn = shard_map(step, mesh=mesh,
                   in_specs=(P(("host", "x", "y"), None),),
                   out_specs=(P(None, None), P(None, None)))
    staged, flat = jax.jit(fn)(vals)
    assert np.array_equal(np.asarray(staged), np.asarray(flat))
    assert int(np.asarray(flat)[0, 0]) == int(vals[:, 0].sum())


def test_sharded_ft_counts_match_single_device_oracle(rng):
    # End to end: the staged reduction must not change what the flat
    # psum reported — sharded counts equal the local kernel's own.
    from ft_sgemm_tpu.ops.ft_sgemm import make_ft_sgemm
    from ft_sgemm_tpu.parallel.sharded import make_mesh, sharded_ft_sgemm

    a, b, c = _operands(rng)
    mesh = make_mesh(8)
    res = sharded_ft_sgemm(a, b, c, mesh, TILE, inject=INJ,
                           strategy="rowcol")
    # Single-device oracle at the same tile: the mesh splits M over 4
    # and K over 2, so per-device fault counts differ — but the global
    # detection count is the sum over devices of what each local kernel
    # detected, which injection-every-step makes deterministic: every
    # local kernel call detects (and corrects) its injected faults.
    assert int(np.sum(np.asarray(res.detections))) > 0
    assert int(np.sum(np.asarray(res.uncorrectable))) == 0
    from ft_sgemm_tpu.ops.reference import sgemm_reference

    want = np.asarray(sgemm_reference(a, b, c, 1.0, -1.5))
    np.testing.assert_allclose(np.asarray(res.c), want, rtol=2e-4,
                               atol=2e-3)


# --- ring_overlap tuner axis --------------------------------------------


def test_make_key_carries_ring_component():
    from ft_sgemm_tpu import tuner

    key = tuner.make_key(256, 256, 256, strategy="weighted",
                         in_dtype="float32", injection_enabled=False,
                         device="x")
    assert "|ring=serial" in key
    auto = tuner.make_key(32, 32, 256, strategy="weighted",
                          in_dtype="float32", injection_enabled=False,
                          ring="auto", device="x")
    assert auto.endswith("|ring=auto")


def test_schema4_cache_misses_cleanly(tmp_path, monkeypatch):
    from ft_sgemm_tpu.tuner import cache as tcache

    assert tcache.SCHEMA_VERSION == 5
    path = tmp_path / "old.json"
    path.write_text(json.dumps({
        "schema": 4,
        "entries": {"cpu|256x256x256|float32|weighted|enc=vpu|thr=static"
                    "|inj=0|pipe=auto|grid=auto|cad=auto|epi=none":
                    {"block": [128, 128, 128]}}}))
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        entries = tcache.load_entries(str(path))
    assert entries == {}
    assert any("schema" in str(w.message) for w in caught)


def test_tune_ring_cost_roundtrip(tmp_path, monkeypatch, rng):
    from ft_sgemm_tpu import tuner

    monkeypatch.setenv("FT_SGEMM_TUNER_CACHE",
                       str(tmp_path / "ring_cache.json"))
    mesh = make_ring_mesh(8)
    report = tuner.tune_ring(256, mesh=mesh, method="cost")
    assert report["winner"] == "overlap"  # d>1: transfers can hide
    assert report["serial"]["score"] > report["overlap"]["score"]
    assert "|ring=auto" in report["key"]
    assert tuner.lookup_ring_overlap(
        32, 32, 256, strategy="weighted", in_dtype="float32") == "overlap"
    # Dispatch consumes the winner; value equality with explicit serial.
    a, b, c = _operands(rng)
    r_auto = ring_ft_sgemm(a, b, c, mesh, TILE, inject=INJ,
                           ring_overlap=None)
    r_serial = ring_ft_sgemm(a, b, c, mesh, TILE, inject=INJ,
                             ring_overlap="serial")
    assert np.array_equal(np.asarray(r_auto.c), np.asarray(r_serial.c))


def test_kernel_variant_ring_field_validated():
    assert KernelVariant().ring_overlap == "serial"
    assert KernelVariant(ring_overlap="overlap").ring_overlap == "overlap"
    with pytest.raises(ValueError, match="ring_overlap"):
        KernelVariant(ring_overlap="sideways")


def test_ring_schedule_cost_model_direction():
    from ft_sgemm_tpu.tuner.measure import ring_schedule_cost

    kw = dict(peak_flops=1e12, itemsize=4)
    serial = ring_schedule_cost(4096, 4096, 4096, 8, overlap=False, **kw)
    overlap = ring_schedule_cost(4096, 4096, 4096, 8, overlap=True, **kw)
    assert overlap < serial
    # Degenerate 1-device ring: overlap pays the extra exposed hop, so
    # the model must NOT prefer it.
    s1 = ring_schedule_cost(512, 512, 512, 1, overlap=False, **kw)
    o1 = ring_schedule_cost(512, 512, 512, 1, overlap=True, **kw)
    assert s1 <= o1


# --- device pool ---------------------------------------------------------


def _mini_buckets():
    from ft_sgemm_tpu.serve import default_bucket_set

    return default_bucket_set((128,))


def test_pool_placement_and_sick_drain(rng):
    from ft_sgemm_tpu.serve import DevicePool, ServeEngine, ServeRequest
    from ft_sgemm_tpu.telemetry.registry import MetricsRegistry

    pool = DevicePool(jax.local_devices()[:4], max_in_flight=2)
    sick = pool.mark_sick(1)
    assert sick == pool.labels[1]
    assert 1 not in pool.eligible()
    with ServeEngine(_mini_buckets(), max_batch=1,
                     registry=MetricsRegistry(), pool=pool) as engine:
        engine.prewarm()
        compiled_after_prewarm = len(engine._compiled)
        futs = []
        reqs = []
        for _ in range(12):
            a = rng.standard_normal((96, 100)).astype(np.float32)
            b = rng.standard_normal((120, 100)).astype(np.float32)
            req = ServeRequest(a=a, b=b, variant="inject")
            reqs.append(req)
            futs.append(engine.submit(req))
        engine.drain(timeout=120)
        results = [f.result(timeout=120) for f in futs]
        stats = engine.stats()
        # Steady state compiled NOTHING beyond prewarm — pool-wide.
        assert len(engine._compiled) == compiled_after_prewarm
    assert all(r.ok for r in results)
    # Correctness through the pool path: every result matches the oracle
    # at the request's true shape (injected faults corrected).
    from ft_sgemm_tpu.ops.reference import sgemm_reference

    for req, r in zip(reqs, results):
        want = np.asarray(sgemm_reference(
            req.a, req.b, np.zeros((96, 120), np.float32), 1.0, 0.0))
        np.testing.assert_allclose(r.c, want, rtol=2e-4, atol=2e-3)
    ps = stats["pool"]
    assert ps["devices_used"] > 1
    assert ps["per_device"][sick]["batches"] == 0
    assert sick in ps["drained"]


def test_pool_round_robin_ignores_health():
    from ft_sgemm_tpu.serve import DevicePool

    pool = DevicePool(jax.local_devices()[:3], placement="round_robin",
                      health=None)
    picks = [pool.choose() for _ in range(6)]
    assert picks == [0, 1, 2, 0, 1, 2]
    assert pool.eligible() == [0, 1, 2]


def test_pool_relative_drain_floor_under_uniform_degradation():
    from ft_sgemm_tpu.serve import DevicePool

    pool = DevicePool(jax.local_devices()[:4])
    # Uniformly-injected fleet: every device corrects SDCs at the same
    # high rate — nobody may be drained over FREE corrected faults.
    for i in range(4):
        pool.health.observe(pool.labels[i], calls=10, detected=30)
    assert pool.eligible() == [0, 1, 2, 3]
    assert pool.stats()["drained"] == []
    # One device decisively sicker (uncorrectables on top): drained.
    pool.health.observe(pool.labels[2], calls=10, detected=40,
                        uncorrectable=40)
    assert 2 not in pool.eligible()
    assert pool.labels[2] in pool.stats()["drained"]


def test_pool_placement_axis_mirrors_contract():
    import ast

    from ft_sgemm_tpu.serve.pool import PLACEMENTS
    from ft_sgemm_tpu.telemetry.events import AXIS_LABELS

    root = pathlib.Path(__file__).resolve().parent.parent
    tree = ast.parse((root / "ft_sgemm_tpu" / "contracts.py").read_text())
    lits = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            try:
                lits[node.targets[0].id] = ast.literal_eval(node.value)
            except ValueError:
                pass
    assert tuple(lits["POOL_PLACEMENTS"]) == PLACEMENTS
    assert tuple(AXIS_LABELS["pool_placement"]) == PLACEMENTS
    assert tuple(lits["VARIANT_AXES"]["ring_overlap"]) == (
        "serial", "overlap")


def test_run_pool_serve_bench_scaling_and_drain(rng):
    from ft_sgemm_tpu.serve import run_pool_serve_bench

    stats = run_pool_serve_bench(
        smoke=True, bucket_sizes=(128,), num_requests=12,
        devices=jax.local_devices()[:3], monitor="auto",
        retry_backoff=0.05)
    assert stats["completed"] == 12
    assert stats["correct"] == 12
    assert stats["goodput_rps"] > 0
    assert stats["single"]["goodput_rps"] > 0
    assert "throughput_ratio" in stats["scaling"]
    assert stats["pool"]["devices_used"] > 1
    assert stats["sick_device"] is not None
    assert stats["sick_device_batches"] == 0
    assert stats["sick_device_drained"] is True


# --- BENCH_r06 / MULTICHIP_r06 contracts --------------------------------

BENCH = pathlib.Path(__file__).resolve().parent.parent / "bench.py"


def test_bench_cpu_fallback_promotes_smoke_headline(tmp_path):
    records = tmp_path / "records.jsonl"
    records.write_text(
        json.dumps({"name": "backend", "ok": True,
                    "value": {"backend": "cpu", "device_kind": "cpu",
                              "platform_used": "cpu"}}) + "\n"
        + json.dumps({"name": "fallback_smoke", "ok": True, "value": {
            "ok": True,
            "encode_modes": {"vpu": {"corrected_ok": True,
                                     "detections": 4,
                                     "uncorrectable": 0,
                                     "seconds": 0.5,
                                     "warm_seconds": 0.004}}}}) + "\n")
    env = dict(os.environ)
    env.update({"FT_SGEMM_BENCH_RECORDS": str(records),
                "FT_SGEMM_BENCH_DEADLINE": "5",
                "FT_SGEMM_BENCH_MIN_ATTEMPT": "99",
                "FT_SGEMM_BENCH_MARGIN": "2"})
    env.pop("FT_SGEMM_BENCH_FAKE_VALUE", None)
    proc = subprocess.run([sys.executable, str(BENCH)], env=env,
                          capture_output=True, text=True, timeout=60)
    line = [ln for ln in proc.stdout.splitlines() if ln.strip()][-1]
    payload = json.loads(line)
    assert proc.returncode == 0
    assert payload["metric"] == "abft_kernel_smoke_gflops_256"
    assert payload["value"] == round(2.0 * 256**3 / 1e9 / 0.004, 3)
    assert payload["vs_baseline"] is None  # never a fake TPU ratio
    assert payload["context"]["headline_fallback"]["size"] == 256


def _load_ledger():
    root = pathlib.Path(__file__).resolve().parent.parent
    path = root / "ft_sgemm_tpu" / "perf" / "ledger.py"
    spec = importlib.util.spec_from_file_location("_ledger_t14", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_multichip_wrapper_with_measurements_ingests_value():
    ledger = _load_ledger()
    artifact = {
        "metric": "serve_goodput_rps", "value": 88.5,
        "unit": "requests/s", "vs_baseline": None,
        "context": {"serve": True, "pool": True, "smoke": True,
                    "completed": 28, "correct": 28,
                    "throughput_rps": 88.5,
                    "p50_latency_seconds": 0.1,
                    "p99_latency_seconds": 0.3,
                    "scaling": {"throughput_ratio": 3.5,
                                "goodput_ratio": 3.5}},
    }
    wrapper = {"n": 6, "n_devices": 8, "rc": 0, "cmd": "bench --pool",
               "tail": "", "parsed": artifact}
    entry = ledger.ingest(wrapper, run_id="MULTICHIP_r06")
    assert entry["kind"] == "multichip"
    assert entry["value"] == 88.5
    assert entry["measurements"]["serve_goodput_rps"]["value"] == 88.5
    assert entry["measurements"]["serve_pool.throughput_ratio"][
        "value"] == 3.5
    assert not any(d.startswith("no_measurements")
                   for d in entry["degradations"])


def test_multichip_flag_only_probe_keeps_degradation():
    ledger = _load_ledger()
    entry = ledger.ingest({"n_devices": 8, "rc": 0, "ok": True,
                           "skipped": False, "tail": ""},
                          run_id="MULTICHIP_r05")
    assert entry["kind"] == "multichip"
    assert entry["value"] == 1.0
    assert "no_measurements:multichip_ok_flag_only" in entry["degradations"]
