"""Timeline recorder: streamed spans, kill-safety, summary, rendering.

The contract (telemetry/timeline.py): every event is durably on disk the
moment it is emitted (a kill loses at most the span in flight, which the
summary then reports AS in flight), the reader tolerates torn tails, and
the module stays stdlib-only so bench.py's jax-free supervisor can load
it by file path.
"""

import json

import pytest

from ft_sgemm_tpu.telemetry.timeline import (
    TimelineRecorder,
    format_timeline,
    read_timeline,
    summarize_timeline,
)


def test_span_roundtrip_with_value(tmp_path):
    path = tmp_path / "tl.jsonl"
    tl = TimelineRecorder(path)
    with tl.span("ft_rowcol", kind="stage") as info:
        info["value"] = 25600.0
    with tl.span("backend_init", kind="compile"):
        pass
    tl.point("heartbeat", "beat")
    tl.close()
    records = read_timeline(path)
    assert [r["phase"] for r in records] == ["start", "end", "start",
                                             "end", "point"]
    summary = summarize_timeline(records)
    assert [s["name"] for s in summary["spans"]] == ["ft_rowcol",
                                                     "backend_init"]
    assert summary["spans"][0]["status"] == "ok"
    assert summary["spans"][0]["value"] == 25600.0
    assert summary["stage_values"] == {"ft_rowcol": 25600.0}
    assert summary["in_flight"] == []
    assert summary["killed_at_stage"] is None
    assert summary["heartbeats"] == 1


def test_failed_span_records_error_and_reraises(tmp_path):
    path = tmp_path / "tl.jsonl"
    tl = TimelineRecorder(path)
    with pytest.raises(RuntimeError, match="boom"):
        with tl.span("xla_dot", kind="stage"):
            raise RuntimeError("boom")
    summary = summarize_timeline(read_timeline(path))
    (span,) = summary["spans"]
    assert span["status"] == "fail" and "boom" in span["error"]
    # Failed stages are NOT salvage material.
    assert summary["stage_values"] == {}


def test_kill_mid_span_leaves_start_on_disk(tmp_path):
    """The whole point: a start record lands BEFORE the work, so a
    SIGKILL mid-stage still names what was in flight, and the kill
    marker the supervisor appends renders with it."""
    path = tmp_path / "tl.jsonl"
    tl = TimelineRecorder(path)
    with tl.span("ft_rowcol", kind="stage") as info:
        info["value"] = 100.0
    # Simulate a kill mid-span: start written, process dies, no end.
    tl._write({"kind": "stage", "name": "ft_fused", "phase": "start",
               "t": 12345.0})
    TimelineRecorder(path).point("kill",
                                 "killed (supervisor deadline reached)")
    with open(path, "ab") as f:
        f.write(b'{"kind": "stage", "name": "torn", "phase": "e')  # torn
    summary = summarize_timeline(read_timeline(path))
    assert summary["killed_at_stage"] == "ft_fused"
    assert summary["stage_values"] == {"ft_rowcol": 100.0}
    assert [k["name"] for k in summary["kills"]] == [
        "killed (supervisor deadline reached)"]
    text = format_timeline(summary)
    assert "IN FLIGHT" in text and "ft_fused" in text
    assert "KILL" in text
    assert "killed during stage: ft_fused" in text


def test_heartbeat_gap_detection(tmp_path):
    path = tmp_path / "tl.jsonl"
    with open(path, "w") as f:
        for t in (0.0, 10.0, 20.0, 95.0):  # one 75 s gap (wedged worker)
            f.write(json.dumps({"kind": "heartbeat", "name": "beat",
                                "phase": "point", "t": t}) + "\n")
    summary = summarize_timeline(read_timeline(path))
    assert summary["heartbeats"] == 4
    assert summary["max_heartbeat_gap"] == pytest.approx(75.0)
    assert "max gap 75.0s" in format_timeline(summary)


def test_reader_skips_foreign_lines(tmp_path):
    path = tmp_path / "tl.jsonl"
    path.write_text('not json\n{"unrelated": 1}\n'
                    + json.dumps({"kind": "stage", "name": "s",
                                  "phase": "start", "t": 1.0}) + "\n")
    records = read_timeline(path)
    assert len(records) == 1 and records[0]["name"] == "s"


def test_module_is_loadable_without_the_package(tmp_path):
    """bench.py's supervisor loads timeline.py by FILE PATH (importing
    the package root would pull jax into the jax-free supervisor); the
    module must work standalone."""
    import importlib.util
    import pathlib

    src = (pathlib.Path(__file__).resolve().parent.parent / "ft_sgemm_tpu"
           / "telemetry" / "timeline.py")
    spec = importlib.util.spec_from_file_location("_standalone_tl", src)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    tl = mod.TimelineRecorder(tmp_path / "x.jsonl")
    with tl.span("s") as info:
        info["value"] = 1.0
    assert mod.summarize_timeline(
        mod.read_timeline(tmp_path / "x.jsonl"))["stage_values"] == {
            "s": 1.0}


def test_cli_timeline_renders_and_errors(tmp_path, capsys):
    from ft_sgemm_tpu import cli

    path = tmp_path / "tl.jsonl"
    tl = TimelineRecorder(path)
    with tl.span("ft_rowcol", kind="stage") as info:
        info["value"] = 321.0
    assert cli.main(["cli", "timeline", str(path)]) == 0
    out = capsys.readouterr().out
    assert "ft_rowcol" in out and "321.0" in out
    assert cli.main(["cli", "timeline", str(path), "--format=json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["stage_values"] == {"ft_rowcol": 321.0}
    # Empty file: exit 1; missing file: exit 2.
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert cli.main(["cli", "timeline", str(empty)]) == 1
    assert cli.main(["cli", "timeline", str(tmp_path / "nope")]) == 2
