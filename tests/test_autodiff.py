"""Differentiable FT matmul: gradients through ABFT-protected GEMMs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ft_sgemm_tpu import InjectionSpec, make_ft_matmul
from ft_sgemm_tpu.configs import KernelShape
from ft_sgemm_tpu.utils import generate_random_matrix, verify_matrix

TILE = KernelShape("t128", 128, 128, 128, (0,) * 7)


def _ab(m, n, k, seed=10):
    rng = np.random.default_rng(seed)
    return (generate_random_matrix(m, k, rng=rng),
            generate_random_matrix(n, k, rng=rng))


def _loss_pair(mm, a, b):
    """Loss through the FT matmul and the identical jnp reference loss."""
    def loss_ft(a, b):
        return jnp.sum(jnp.tanh(mm(a, b)))

    def loss_ref(a, b):
        return jnp.sum(jnp.tanh(a @ b.T))

    return loss_ft, loss_ref


def test_forward_and_grads_match_reference():
    a, b = _ab(256, 128, 256)
    mm = make_ft_matmul(TILE)
    loss_ft, loss_ref = _loss_pair(mm, a, b)
    ga, gb = jax.grad(loss_ft, argnums=(0, 1))(a, b)
    ra, rb = jax.grad(loss_ref, argnums=(0, 1))(a, b)
    np.testing.assert_allclose(np.asarray(ga), np.asarray(ra),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(rb),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("strategy", ["rowcol", "weighted"])
def test_injected_faults_corrected_in_fwd_and_bwd(strategy):
    a, b = _ab(256, 128, 256, seed=3)
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    mm = make_ft_matmul(TILE, strategy=strategy, inject=inj)
    loss_ft, loss_ref = _loss_pair(mm, a, b)
    ga, gb = jax.grad(loss_ft, argnums=(0, 1))(a, b)
    ra, rb = jax.grad(loss_ref, argnums=(0, 1))(a, b)
    # All three GEMMs (fwd, dA, dB) inject and must self-correct: grads
    # match the clean reference under the framework acceptance tolerance.
    for got, want, name in ((ga, ra, "dA"), (gb, rb, "dB")):
        ok, nbad, _ = verify_matrix(np.asarray(want), np.asarray(got),
                                    verbose=False)
        assert ok, f"{strategy}/{name}: {nbad} corrupted elements survived"


def test_bwd_threshold_catches_small_faults():
    """Gradient-scale SDC sits below the forward-calibrated 9500 threshold
    (the documented blind spot); a tightened threshold catches and corrects
    it. Shown as a contrast pair on magnitude-100 faults."""
    a, b = _ab(256, 128, 256, seed=9)
    inj = InjectionSpec(enabled=True, every=1, magnitude=100.0)
    _, loss_ref = _loss_pair(None, a, b)
    ra, rb = jax.grad(loss_ref, argnums=(0, 1))(a, b)

    # Default threshold (9500): 100-magnitude faults pass undetected.
    mm = make_ft_matmul(TILE, inject=inj)
    ga, gb = jax.grad(_loss_pair(mm, a, b)[0], argnums=(0, 1))(a, b)
    ok_a, _, _ = verify_matrix(np.asarray(ra), np.asarray(ga), verbose=False)
    ok_b, _, _ = verify_matrix(np.asarray(rb), np.asarray(gb), verbose=False)
    assert not (ok_a and ok_b), "sub-threshold faults should have survived"

    # Tightened thresholds (50, above this size's noise floor): corrected.
    mm = make_ft_matmul(TILE, inject=inj, threshold=50.0, bwd_threshold=50.0)
    ga, gb = jax.grad(_loss_pair(mm, a, b)[0], argnums=(0, 1))(a, b)
    for got, want, name in ((ga, ra, "dA"), (gb, rb, "dB")):
        ok, nbad, _ = verify_matrix(np.asarray(want), np.asarray(got),
                                    verbose=False)
        assert ok, f"{name}: {nbad} small faults survived tight threshold"


def test_detect_only_strategy_rejected():
    """Both factories refuse detect-only 'global' even with
    with_counts=True: counts cover the FORWARD GEMMs only — a custom_vjp
    backward has no primal channel, so detect-only backward faults would
    be neither corrected nor observable."""
    from ft_sgemm_tpu import make_ft_attention_diff

    with pytest.raises(ValueError, match="CORRECTING"):
        make_ft_matmul(TILE, strategy="global")
    with pytest.raises(ValueError, match="CORRECTING"):
        make_ft_attention_diff(strategy="global")
    with pytest.raises(ValueError, match="CORRECTING"):
        make_ft_matmul(TILE, strategy="global", with_counts=True)
    with pytest.raises(ValueError, match="CORRECTING"):
        make_ft_attention_diff(strategy="global", with_counts=True)


def test_with_counts_observable_under_grad():
    """with_counts=True returns (out, counts): gradients flow through out
    (unchanged vs the reference), while the int32 counts leaf reports the
    forward GEMM's corrected faults every step — including under jit."""
    a, b = _ab(256, 128, 256, seed=4)
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    mm = make_ft_matmul(TILE, inject=inj, with_counts=True)

    def loss(a, b):
        r = mm(a, b)
        return jnp.sum(jnp.tanh(r.out)), (r.detections, r.uncorrectable)

    (val, (counts, unc)), (ga, gb) = jax.jit(
        jax.value_and_grad(loss, argnums=(0, 1), has_aux=True))(a, b)
    _, loss_ref = _loss_pair(None, a, b)
    ra, rb = jax.grad(loss_ref, argnums=(0, 1))(a, b)
    assert int(counts) > 0, "injected faults must be counted"
    assert int(unc) == 0, "rotating injector must stay correctable"
    np.testing.assert_allclose(float(val), float(loss_ref(a, b)),
                               rtol=1e-4)
    for got, want in ((ga, ra), (gb, rb)):
        ok, nbad, _ = verify_matrix(np.asarray(want), np.asarray(got),
                                    verbose=False)
        assert ok, f"{nbad} corrupted gradient elements survived"

    # Clean build: counts must be exactly zero.
    mm_clean = make_ft_matmul(TILE, with_counts=True)
    res = mm_clean(a, b)
    assert int(res.detections) == 0 and int(res.uncorrectable) == 0


def test_attention_diff_with_counts():
    """with_counts=True on the differentiable attention returns the full
    FtAttentionResult pytree: detections cover both forward GEMMs, the
    softmax rowsum invariant is restored, and grads still match."""
    from ft_sgemm_tpu import (attention_reference, make_ft_attention_diff)
    from ft_sgemm_tpu.ops.attention import FtAttentionResult

    rng = np.random.default_rng(13)
    l, d = 256, 128
    q, k, v = (generate_random_matrix(l, d, rng=rng) for _ in range(3))
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    att = make_ft_attention_diff(inject=inj, with_counts=True)

    res = att(q, k, v)
    assert isinstance(res, FtAttentionResult)
    assert int(res.detections) > 0
    assert int(res.softmax_flags) == 0
    assert int(res.uncorrectable) == 0

    def loss(q, k, v):
        r = att(q, k, v)
        return jnp.sum(jnp.tanh(r.out)), (r.detections, r.softmax_flags)

    (val, (det, flags)), grads = jax.jit(jax.value_and_grad(
        loss, argnums=(0, 1, 2), has_aux=True))(q, k, v)
    assert int(det) > 0 and int(flags) == 0

    def loss_ref(q, k, v):
        return jnp.sum(jnp.tanh(attention_reference(q, k, v)))

    want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for g, w, name in zip(grads, want, ("dQ", "dK", "dV")):
        ok, nbad, _ = verify_matrix(np.asarray(w), np.asarray(g),
                                    verbose=False)
        assert ok, f"{name}: {nbad} corrupted elements survived"


def test_composes_with_jit_and_vmap():
    a, b = _ab(128, 128, 128, seed=5)
    mm = make_ft_matmul(TILE)
    out = jax.jit(mm)(a, b)
    np.testing.assert_allclose(np.asarray(out), a @ b.T, rtol=1e-4,
                               atol=1e-5)
    ab = jnp.stack([a, a * 0.5])
    bb = jnp.stack([b, b * 2.0])
    outs = jax.vmap(mm)(ab, bb)
    np.testing.assert_allclose(np.asarray(outs[1]), a @ b.T, rtol=1e-4,
                               atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ft_attention_diff_grads_match_reference(causal):
    """All six GEMMs (2 fwd + 4 bwd) ABFT-protected: attention gradients
    match the plain-JAX reference, clean AND with injection on."""
    from ft_sgemm_tpu import attention_reference, make_ft_attention_diff

    rng = np.random.default_rng(11)
    l, d = 256, 128
    q, k, v = (generate_random_matrix(l, d, rng=rng) for _ in range(3))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.tanh(
            attention_reference(q, k, v, causal=causal)))

    want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)

    att = make_ft_attention_diff(causal=causal)
    got = jax.grad(lambda q, k, v: jnp.sum(jnp.tanh(att(q, k, v))),
                   argnums=(0, 1, 2))(q, k, v)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=1e-4, atol=1e-5)

    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    att = make_ft_attention_diff(causal=causal, inject=inj)
    got = jax.grad(lambda q, k, v: jnp.sum(jnp.tanh(att(q, k, v))),
                   argnums=(0, 1, 2))(q, k, v)
    for g, w, name in zip(got, want, ("dQ", "dK", "dV")):
        ok, nbad, _ = verify_matrix(np.asarray(w), np.asarray(g),
                                    verbose=False)
        assert ok, f"{name}: {nbad} corrupted elements survived"


def test_training_step_converges_under_injection():
    """A full SGD step sequence on a linear model with every GEMM
    ABFT-protected and faults injected throughout: the model still fits —
    the end-to-end claim (SDC cannot poison training)."""
    rng = np.random.default_rng(7)
    m, k, n = 128, 128, 128
    x = generate_random_matrix(m, k, rng=rng)
    w_true = generate_random_matrix(n, k, rng=rng)
    y = x @ w_true.T
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    mm = make_ft_matmul(TILE, inject=inj)

    def loss(w):
        return jnp.mean((mm(x, w) - y) ** 2)

    # lr ~ 2/(lambda_max + lambda_min) of the quadratic's Hessian
    # (2 X^T X / MN, lambda_max ~ 0.017 for these inputs).
    step = jax.jit(lambda w: w - 110.0 * jax.grad(loss)(w))
    w = jnp.zeros_like(w_true)
    l0 = float(loss(w))
    for _ in range(60):
        w = step(w)
    l1 = float(loss(w))
    assert l1 < 1e-2 * l0, (l0, l1)


def test_auto_threshold_closes_gradient_scale_blind_spot():
    """The documented blind spot: gradient-scale SDC sits below a
    forward-calibrated fixed threshold (test_bwd_threshold_catches_small_
    faults works around it by hand-picking 50.0). threshold='auto'
    removes the hand-tuning: each GEMM's threshold is computed from ITS
    OWN operands' moments — the backward GEMMs see cotangent-scale
    inputs and calibrate to them automatically."""
    a, b = _ab(256, 128, 256, seed=9)
    inj = InjectionSpec(enabled=True, every=1, magnitude=100.0)
    _, loss_ref = _loss_pair(None, a, b)
    ra, rb = jax.grad(loss_ref, argnums=(0, 1))(a, b)

    mm = make_ft_matmul(TILE, inject=inj, threshold="auto")
    ga, gb = jax.grad(_loss_pair(mm, a, b)[0], argnums=(0, 1))(a, b)
    for got, want, name in ((ga, ra, "dA"), (gb, rb, "dB")):
        ok, nbad, _ = verify_matrix(np.asarray(want), np.asarray(got),
                                    verbose=False)
        assert ok, f"{name}: {nbad} gradient-scale faults survived auto"


def test_auto_threshold_ft_attention():
    """Auto thresholds flow through the attention factory: both GEMMs
    calibrate to their own operand scales (P's entries are probabilities
    ~1/Lk — far below Q/K scale) and tiny faults are corrected."""
    from ft_sgemm_tpu import attention_reference, make_ft_attention

    rng = np.random.default_rng(15)
    l, d = 256, 128
    q, k, v = (generate_random_matrix(l, d, rng=rng) for _ in range(3))
    inj = InjectionSpec(enabled=True, every=1, magnitude=1.0)
    att = make_ft_attention(threshold="auto",
                            qk_shape=TILE, pv_shape=TILE)
    res = att(q, k, v, inject=inj)
    want = np.asarray(attention_reference(q, k, v))
    ok, nbad, _ = verify_matrix(want, np.asarray(res.out), verbose=False)
    assert ok, f"{nbad} tiny faults survived auto-threshold attention"
    assert int(res.detections) > 0
    assert int(res.uncorrectable) == 0
