"""summarize_bench renders banked records with bench.py's semantics."""

import pathlib
import subprocess
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent


def test_summarizer_handles_resume_artifacts(tmp_path):
    p = tmp_path / "records_key_4096.jsonl"
    with open(p, "w") as f:
        f.write("42\n")  # stray scalar line (resumed-file artifact)
        f.write('{"name":"backend","ok":true,"value":'
                '{"backend":"tpu","device":"d","num_devices":1}}\n')
        f.write('{"name":"xla_dot","ok":true,"value":32000.0}\n')
        f.write('{"name":"ft_rowcol","ok":false,"error":"skipped"}\n')
        f.write('{"name":"ft_rowcol","ok":true,"value":25600.0}\n')
        f.write('{"name":"backend_guard","ok":true,"value":"cleared: x"}\n')
    with open(p, "ab") as f:
        f.write(b'{"name":"torn","ok":true,"value":"\xc3')  # torn write
    out = subprocess.run(
        [sys.executable, "scripts/summarize_bench.py", str(p)],
        cwd=ROOT, capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr[-500:]
    assert "ft_rowcol" in out.stdout and "25600.0" in out.stdout
    assert "80.0% of xla_dot" in out.stdout
    # Later ok wins: the superseded error must not be reported.
    assert "ERROR" not in out.stdout
    # Tombstones are provenance, not measurement rows.
    assert "backend_guard" not in out.stdout
