"""summarize_bench renders banked records with bench.py's semantics."""

import json
import pathlib
import subprocess
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent


def test_summarizer_handles_resume_artifacts(tmp_path):
    p = tmp_path / "records_key_4096.jsonl"
    with open(p, "w") as f:
        f.write("42\n")  # stray scalar line (resumed-file artifact)
        f.write('{"name":"backend","ok":true,"value":'
                '{"backend":"tpu","device":"d","num_devices":1}}\n')
        f.write('{"name":"xla_dot","ok":true,"value":32000.0}\n')
        f.write('{"name":"ft_rowcol","ok":false,"error":"skipped"}\n')
        f.write('{"name":"ft_rowcol","ok":true,"value":25600.0}\n')
        f.write('{"name":"backend_guard","ok":true,"value":"cleared: x"}\n')
    with open(p, "ab") as f:
        f.write(b'{"name":"torn","ok":true,"value":"\xc3')  # torn write
    out = subprocess.run(
        [sys.executable, "scripts/summarize_bench.py", str(p)],
        cwd=ROOT, capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr[-500:]
    assert "ft_rowcol" in out.stdout and "25600.0" in out.stdout
    assert "80.0% of xla_dot" in out.stdout
    # Later ok wins: the superseded error must not be reported.
    assert "ERROR" not in out.stdout
    # Tombstones are provenance, not measurement rows.
    assert "backend_guard" not in out.stdout


def test_summarizer_annotates_partial_salvaged_artifact(tmp_path):
    """A salvaged bench ARTIFACT (context.partial from a deadline-killed
    run) must render — not crash — and be annotated PARTIAL with its
    kill point, so it is never mistaken for a full sweep."""
    p = tmp_path / "artifact.json"
    p.write_text(json.dumps({
        "metric": "abft_kernel_huge_gflops_4096", "value": 25600.0,
        "unit": "GFLOPS", "vs_baseline": 6.392,
        "context": {"partial": True, "killed_at_stage": "ft_fused",
                    "completed_stages": ["backend", "ft_rowcol"],
                    "errors": {"worker_rc":
                               "killed (supervisor deadline reached)"}},
    }))
    out = subprocess.run(
        [sys.executable, "scripts/summarize_bench.py", str(p)],
        cwd=ROOT, capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr[-500:]
    assert "25600.0" in out.stdout
    assert "PARTIAL" in out.stdout
    assert "ft_fused" in out.stdout
    assert "backend, ft_rowcol" in out.stdout
    # A full (non-partial) artifact renders without the annotation.
    full = tmp_path / "full.json"
    full.write_text(json.dumps({
        "metric": "bench_smoke", "value": 1, "unit": "ok",
        "vs_baseline": None, "context": {}}))
    out = subprocess.run(
        [sys.executable, "scripts/summarize_bench.py", str(full)],
        cwd=ROOT, capture_output=True, text=True, timeout=120)
    assert out.returncode == 0
    assert "PARTIAL" not in out.stdout


def test_summarizer_surfaces_slo_section(tmp_path):
    """A serving artifact's slo section (telemetry/monitor.py snapshot)
    renders as summary rows: status + named reasons, error budget
    remaining / burn rate, and the device-health minimum with the worst
    device named — and an artifact WITHOUT one renders no slo rows."""
    p = tmp_path / "serve_artifact.json"
    p.write_text(json.dumps({
        "metric": "serve_goodput_rps", "value": 4.2,
        "unit": "requests/s", "vs_baseline": None,
        "context": {
            "goodput_rps": 4.2,
            "slo": {"status": "DEGRADED",
                    "reasons": ["device TFRT_CPU_6 health 0.368 "
                                "below 0.9"],
                    "budget_remaining": 0.75, "burn_rate": 0.25,
                    "goodput_ratio": 0.99,
                    "device_health": {"TFRT_CPU_0": 1.0,
                                      "TFRT_CPU_6": 0.368},
                    "device_health_min": 0.368}},
    }))
    out = subprocess.run(
        [sys.executable, "scripts/summarize_bench.py", str(p)],
        cwd=ROOT, capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr[-500:]
    assert "slo status" in out.stdout and "DEGRADED" in out.stdout
    assert "TFRT_CPU_6 health 0.368" in out.stdout
    assert "remaining 0.75" in out.stdout and "burn 0.25x" in out.stdout
    assert "device health min" in out.stdout
    assert "(worst: TFRT_CPU_6)" in out.stdout
    # No slo section -> no slo rows.
    bare = tmp_path / "bare.json"
    bare.write_text(json.dumps({
        "metric": "serve_goodput_rps", "value": 1.0, "unit": "requests/s",
        "vs_baseline": None, "context": {}}))
    out = subprocess.run(
        [sys.executable, "scripts/summarize_bench.py", str(bare)],
        cwd=ROOT, capture_output=True, text=True, timeout=120)
    assert out.returncode == 0
    assert "slo status" not in out.stdout


def test_summarizer_surfaces_economics_and_fleet_hops(tmp_path):
    """ISSUE 20 satellite: the summarizer renders the cost-economics
    rows (useful-flops fraction, overhead causes, correct-token
    throughput) and the per-host fleet rows (request counts, measured
    clock skew, hop p95s) — tolerantly, so a hostile/partial dispatcher
    block renders what it can instead of crashing."""
    p = tmp_path / "fleet_artifact.json"
    p.write_text(json.dumps({
        "metric": "fleet_smoke", "value": 1.0, "unit": "ok",
        "vs_baseline": None,
        "context": {
            "economics": {
                "useful_flops_fraction": 0.8542,
                "flops_total": 2.5e9, "requests": 16,
                "overhead_fractions": {"encode": 0.06, "check": 0.02,
                                       "retry": 0.0658, "recompute": 0,
                                       "kv_reverify": 0},
                "tokens_correct_per_second_per_device": 41.5,
                "tokens_correct": 2048, "tokens": 2048},
            "fleet": {"dispatcher": {"per_host": {
                "0": {"requests": 9},
                "1": {"requests": 7,
                      "clock_skew_seconds": 0.0123,
                      "hop_percentiles": {
                          "rtt": {"p50": 0.001, "p95": 0.0042},
                          "remote_execute": {"p95": "broken"}}},
                "2": "hostile-not-a-dict"}}}},
    }))
    out = subprocess.run(
        [sys.executable, "scripts/summarize_bench.py", str(p)],
        cwd=ROOT, capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr[-500:]
    assert "economics useful flops" in out.stdout
    assert "0.8542" in out.stdout and "16 requests" in out.stdout
    assert "retry=0.0658" in out.stdout
    # Zero-valued causes are noise, not rows.
    assert "recompute" not in out.stdout
    assert "tokens-correct/s/device" in out.stdout
    assert "41.5" in out.stdout and "2048 correct" in out.stdout
    assert "fleet host 0" in out.stdout and "reqs 9" in out.stdout
    assert "fleet host 1" in out.stdout
    assert "skew +0.0123s" in out.stdout
    assert "rtt[p95]=0.0042s" in out.stdout
    # The broken percentile dict and hostile host row render nothing —
    # and crash nothing.
    assert "remote_execute" not in out.stdout
    # Artifacts without the blocks render none of the rows.
    bare = tmp_path / "bare.json"
    bare.write_text(json.dumps({
        "metric": "fleet_smoke", "value": 1.0, "unit": "ok",
        "vs_baseline": None, "context": {}}))
    out = subprocess.run(
        [sys.executable, "scripts/summarize_bench.py", str(bare)],
        cwd=ROOT, capture_output=True, text=True, timeout=120)
    assert out.returncode == 0
    assert "economics" not in out.stdout
    assert "fleet host" not in out.stdout
