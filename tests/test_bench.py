"""bench.py resilience: the JSON line must survive every failure mode.

Round-1 postmortem: BENCH_r01.json recorded rc=1 with no JSON because a
transient axon backend-init failure escaped as a traceback.  Round-2
postmortem: BENCH_r02.json recorded rc=124 because backend init HUNG in C
code — unkillable from Python in-process — and the driver SIGKILLed the
whole script before any JSON flushed.  The round-3 rework answers with a
supervisor/worker split; these tests pin its guarantees end to end with
real subprocesses (the worker's test hooks avoid any jax import):

* a hung worker is killed at its budget and the JSON line still prints;
* a successful worker's stage records become the JSON line (rc 0);
* SIGTERM to the supervisor kills the worker and flushes the JSON line;
* pre-existing stage records (a resumed/partial run) are honored;
* the worker-side _retry helper records errors instead of raising.
"""

import importlib.util
import json
import os
import pathlib
import signal
import subprocess
import sys
import time

BENCH = pathlib.Path(__file__).resolve().parent.parent / "bench.py"


def _load_bench():
    spec = importlib.util.spec_from_file_location("bench_under_test", BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _env(tmp_path, **overrides):
    env = dict(os.environ)
    env.pop("FT_SGEMM_BENCH_FAKE_VALUE", None)
    env.pop("FT_SGEMM_BENCH_FAKE_HANG", None)
    env.update({
        "FT_SGEMM_BENCH_RECORDS": str(tmp_path / "records.jsonl"),
        "FT_SGEMM_BENCH_MARGIN": "2",
        "FT_SGEMM_BENCH_GRACE": "1",
        "FT_SGEMM_BENCH_MIN_ATTEMPT": "1",
    })
    env.update({k: str(v) for k, v in overrides.items()})
    return env


def _run(env, timeout=60):
    return subprocess.run([sys.executable, str(BENCH)], env=env,
                          capture_output=True, text=True, timeout=timeout)


def _payload(proc):
    lines = [l for l in proc.stdout.strip().splitlines() if l.strip()]
    assert lines, f"no stdout; stderr={proc.stderr[-2000:]}"
    return json.loads(lines[-1])


def test_success_path_emits_headline_and_rc0(tmp_path):
    proc = _run(_env(tmp_path, FT_SGEMM_BENCH_DEADLINE="30",
                     FT_SGEMM_BENCH_FAKE_VALUE="28510.0"))
    payload = _payload(proc)
    assert proc.returncode == 0
    assert payload["metric"] == "abft_kernel_huge_gflops_4096"
    assert payload["value"] == 28510.0
    assert abs(payload["vs_baseline"] - 28510.0 / 4005.0) < 1e-3
    assert payload["context"]["strategy"] == "fake"
    assert payload["context"]["backend"] == "fake"
    # ratio assembled across stage records by the supervisor
    assert abs(payload["context"]["ft_vs_xla"] - 1 / 1.05) < 1e-2


def test_hung_worker_is_killed_and_json_still_prints(tmp_path):
    t0 = time.monotonic()
    proc = _run(_env(tmp_path, FT_SGEMM_BENCH_DEADLINE="10",
                     FT_SGEMM_BENCH_WORKER_MAX="2",
                     FT_SGEMM_BENCH_FAKE_HANG="1"))
    payload = _payload(proc)
    assert proc.returncode == 1
    assert payload["value"] is None
    assert payload["context"]["bench_attempts"] >= 1
    assert "worker_rc" in payload["context"]["errors"]
    # ~10s deadline + margin; far below any driver window
    assert time.monotonic() - t0 < 30


def test_heartbeat_extends_attempt_past_nominal_budget(tmp_path):
    """BENCH_r03 regression: a worker still alive (heartbeating) past its
    nominal budget — e.g. a slowly-initializing backend — must be extended
    to completion, not killed. Here the fake worker sleeps 3x its nominal
    2 s budget before recording the headline."""
    proc = _run(_env(tmp_path, FT_SGEMM_BENCH_DEADLINE="40",
                     FT_SGEMM_BENCH_WORKER_MAX="2",
                     FT_SGEMM_BENCH_EXTEND_MAX="30",
                     FT_SGEMM_BENCH_FAKE_VALUE="28510.0",
                     FT_SGEMM_BENCH_FAKE_SLOW="6"))
    payload = _payload(proc)
    assert proc.returncode == 0
    assert payload["value"] == 28510.0
    assert payload["context"]["bench_attempts"] == 1, (
        "the slow worker should survive its first attempt, not be killed "
        "and relaunched")


def test_extension_cap_bounds_a_heartbeating_hang(tmp_path):
    """Liveness is not progress: a worker that heartbeats but never
    completes (dead tunnel hang in a GIL-releasing read) is killed once
    the extension cap is spent, preserving relaunch budget."""
    t0 = time.monotonic()
    proc = _run(_env(tmp_path, FT_SGEMM_BENCH_DEADLINE="16",
                     FT_SGEMM_BENCH_WORKER_MAX="2",
                     FT_SGEMM_BENCH_EXTEND_MAX="2",
                     FT_SGEMM_BENCH_MIN_ATTEMPT="10",
                     FT_SGEMM_BENCH_FAKE_HANG="1"))
    payload = _payload(proc)
    assert proc.returncode == 1
    assert payload["value"] is None
    assert ("heartbeat-extension cap exhausted"
            in payload["context"]["errors"]["worker_rc"])
    assert time.monotonic() - t0 < 35


def test_stale_heartbeat_is_killed_at_nominal_budget(tmp_path):
    """Extension requires a LIVE heartbeat: a worker whose beats never
    start (wedged before the thread could run) is killed at its nominal
    budget, preserving the round-3 kill guarantee."""
    t0 = time.monotonic()
    # MIN_ATTEMPT sized so the run ends after the first kill: the final
    # worker_rc in the artifact is then the stale-heartbeat kill itself.
    # HB_FRESH shrinks the startup-grace window below the extension cap
    # (raised out of the way) so absence, not the cap, triggers the kill.
    proc = _run(_env(tmp_path, FT_SGEMM_BENCH_DEADLINE="12",
                     FT_SGEMM_BENCH_WORKER_MAX="2",
                     FT_SGEMM_BENCH_MIN_ATTEMPT="8",
                     FT_SGEMM_BENCH_HB_FRESH="3",
                     FT_SGEMM_BENCH_EXTEND_MAX="60",
                     FT_SGEMM_BENCH_FAKE_HANG="1",
                     FT_SGEMM_BENCH_FAKE_NO_HB="1"))
    payload = _payload(proc)
    assert proc.returncode == 1
    assert payload["value"] is None
    assert "heartbeat absent" in payload["context"]["errors"]["worker_rc"]
    assert time.monotonic() - t0 < 35


def test_attempt_budget_sizes_one_long_attempt_when_short(monkeypatch):
    """With less than two nominal attempts of budget left, all of it goes
    to a single attempt (two doomed 480 s attempts can't survive a
    ~9-minute init; one 870 s attempt can)."""
    bench = _load_bench()
    monkeypatch.setattr(bench, "_WORKER_MAX", 480.0)
    assert bench._attempt_budget(870.0) == 870.0
    assert bench._attempt_budget(959.9) == 959.9
    assert bench._attempt_budget(960.0) == 480.0
    assert bench._attempt_budget(2000.0) == 480.0


def test_sigterm_flushes_json_before_exit(tmp_path):
    env = _env(tmp_path, FT_SGEMM_BENCH_DEADLINE="120",
               FT_SGEMM_BENCH_WORKER_MAX="100",
               FT_SGEMM_BENCH_FAKE_HANG="1")
    proc = subprocess.Popen([sys.executable, str(BENCH)], env=env,
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True)
    # Wait until the (hanging) worker exists: the supervisor installs its
    # SIGTERM handler BEFORE launching workers, so worker presence proves
    # the handler is active (a fixed sleep races with interpreter startup).
    records = tmp_path / "records.jsonl"
    for _ in range(100):
        out = subprocess.run(["pgrep", "-f", str(records)],
                             capture_output=True, text=True)
        if out.stdout.split():
            break
        time.sleep(0.2)
    else:
        raise AssertionError("worker never launched")
    proc.send_signal(signal.SIGTERM)
    out, err = proc.communicate(timeout=30)
    lines = [l for l in out.strip().splitlines() if l.strip()]
    assert lines, f"no stdout after SIGTERM; stderr={err[-2000:]}"
    payload = json.loads(lines[-1])
    assert proc.returncode == 1
    assert payload["value"] is None
    assert "signal" in payload["context"]["errors"]


def _alive(pid):
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


def test_sigkilled_supervisor_does_not_orphan_worker(tmp_path):
    """PR_SET_PDEATHSIG: a driver that SIGKILLs the supervisor without a
    SIGTERM must not leave a hung worker holding the TPU tunnel."""
    records = tmp_path / "records.jsonl"
    env = _env(tmp_path, FT_SGEMM_BENCH_DEADLINE="120",
               FT_SGEMM_BENCH_WORKER_MAX="100",
               FT_SGEMM_BENCH_FAKE_HANG="1")
    proc = subprocess.Popen([sys.executable, str(BENCH)], env=env,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    try:
        worker_pids = []
        for _ in range(50):  # worker argv contains the unique records path
            out = subprocess.run(["pgrep", "-f", str(records)],
                                 capture_output=True, text=True)
            worker_pids = [int(x) for x in out.stdout.split()]
            if worker_pids:
                break
            time.sleep(0.2)
        assert worker_pids, "worker never launched"
        proc.kill()  # SIGKILL: no handler runs in the supervisor
        proc.wait(timeout=10)
        deadline = time.time() + 10
        while time.time() < deadline and any(map(_alive, worker_pids)):
            time.sleep(0.3)
        assert not any(map(_alive, worker_pids)), "worker orphaned"
    finally:
        if proc.poll() is None:
            proc.kill()
        for pid in worker_pids:
            if _alive(pid):
                os.kill(pid, signal.SIGKILL)


def test_preseeded_records_are_emitted_without_worker(tmp_path):
    records = tmp_path / "records.jsonl"
    records.write_text(
        json.dumps({"name": "ft_headline", "ok": True,
                    "value": {"gflops": 30350.0, "strategy": "weighted"}})
        + "\n"
        + json.dumps({"name": "xla_dot", "ok": True, "value": 32180.0})
        + "\n"
        + json.dumps({"name": "plain_huge", "ok": True, "value": 31000.0})
        + "\n"
        + json.dumps({"name": "bf16_abft", "ok": False, "error": "boom"})
        + "\n")
    # Deadline below MIN_ATTEMPT: supervisor must emit from disk, no worker.
    proc = _run(_env(tmp_path, FT_SGEMM_BENCH_DEADLINE="5",
                     FT_SGEMM_BENCH_MIN_ATTEMPT="99"))
    payload = _payload(proc)
    assert proc.returncode == 0
    assert payload["value"] == 30350.0
    assert payload["context"]["strategy"] == "weighted"
    assert abs(payload["context"]["ft_vs_xla"] - 30350.0 / 32180.0) < 1e-3
    assert abs(payload["context"]["abft_overhead"]
               - (1 - 30350.0 / 31000.0)) < 1e-3
    assert payload["context"]["errors"]["bf16_abft"] == "boom"
    # Provenance: pre-existing stage records are declared, not hidden.
    assert payload["context"]["resumed_stages"] == 3


def test_default_records_path_is_code_version_keyed():
    """Without FT_SGEMM_BENCH_RECORDS, runs of the same code version share
    a stable, repo-local records path (monitoring runs earlier in a round
    hand their measurements to the final scoring run), while a different
    code version can never inherit stale numbers."""
    import re
    import shutil

    bench = _load_bench()
    if not (shutil.which("git") and bench._code_version_key()):
        import pytest

        pytest.skip("no git checkout: default falls back to private mkstemp")
    p1 = bench._default_records_path()
    p2 = bench._default_records_path()
    assert p1 == p2, "same code version must map to the same path"
    assert re.search(
        r"\.bench/records_[0-9a-f]+(-[0-9a-f]{8})?_4096\.jsonl$", p1), p1
    # Repo-local, not the shared world-writable temp dir (the repo itself
    # may legitimately live under /tmp, so compare against bench's dir).
    assert p1.startswith(os.path.join(str(BENCH.parent), ".bench")), p1


def test_run_lock_isolates_concurrent_runs(tmp_path):
    """A second bench against an already-locked records file must fall
    back to a private file instead of racing the first run's appends."""
    import fcntl

    bench = _load_bench()
    records = tmp_path / "records.jsonl"
    holder = open(str(records) + ".lock", "a")
    fcntl.flock(holder, fcntl.LOCK_EX | fcntl.LOCK_NB)
    bench._RECORDS_PATH = str(records)
    bench._DEADLINE = 1.0  # bounds the wait loop to well under a second
    bench._acquire_run_lock()
    assert bench._RECORDS_PATH != str(records), (
        "locked records file must not be shared")
    holder.close()


def test_records_merge_later_lines_win_and_torn_lines_skipped(tmp_path):
    bench = _load_bench()
    path = tmp_path / "r.jsonl"
    path.write_text(
        json.dumps({"name": "xla_dot", "ok": False, "error": "flaky"}) + "\n"
        + json.dumps({"name": "xla_dot", "ok": True, "value": 1.0}) + "\n"
        + '{"name": "plain_huge", "ok": true, "va')  # torn write
    values, errors = bench._read_records(str(path))
    assert values == {"xla_dot": 1.0}
    assert errors == {}


def test_retry_records_error_and_returns_none(monkeypatch):
    bench = _load_bench()
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    errors = {}
    calls = []

    def fails():
        calls.append(1)
        raise RuntimeError("Unable to initialize backend 'axon'")

    assert bench._retry("stage", fails, errors, attempts=3) is None
    assert len(calls) == 3
    assert "Unable to initialize backend" in errors["stage"]


def test_retry_succeeds_after_transient_failure(monkeypatch):
    bench = _load_bench()
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    errors = {}
    state = {"n": 0}

    def flaky():
        state["n"] += 1
        if state["n"] < 3:
            raise RuntimeError("HTTP 500 from tpu_compile_helper")
        return 42

    assert bench._retry("stage", flaky, errors, attempts=4) == 42
    assert errors == {}


def test_headline_picks_best_correcting_variant(tmp_path):
    """All correcting variants qualify as the flagship FT row; the emitted
    headline must be the fastest one measured, with per-variant numbers
    preserved in context."""
    records = tmp_path / "records.jsonl"
    records.write_text(
        json.dumps({"name": "ft_headline", "ok": True,
                    "value": {"gflops": 30000.0, "strategy": "weighted"}})
        + "\n"
        + json.dumps({"name": "ft_fused", "ok": True, "value": 31000.0})
        + "\n"
        + json.dumps({"name": "ft_rowcol", "ok": True, "value": 29000.0})
        + "\n")
    proc = _run(_env(tmp_path, FT_SGEMM_BENCH_DEADLINE="5",
                     FT_SGEMM_BENCH_MIN_ATTEMPT="99"))
    payload = _payload(proc)
    assert proc.returncode == 0
    assert payload["value"] == 31000.0
    assert payload["context"]["strategy"] == "fused (MXU-augmented)"
    assert payload["context"]["abft_fused_gflops"] == 31000.0
    assert payload["context"]["abft_rowcol_gflops"] == 29000.0


def test_recorder_reset_writes_fresh_token(tmp_path):
    bench = _load_bench()
    path = str(tmp_path / "r.jsonl")
    rec = bench.Recorder(path)
    rec.ok("ft_headline", {"gflops": 1.0, "strategy": "weighted"})
    rec.reset()
    values, errors = bench._read_records(path)
    assert "ft_headline" not in values, "reset must discard stages"
    tok1 = values["_reset_token"]
    rec.reset()
    tok2 = bench._read_records(path)[0]["_reset_token"]
    assert tok1 != tok2, "each reset must mint a fresh token"


def test_resumed_stages_suppressed_after_reset(tmp_path):
    """A fresh reset token proves the pre-run records were discarded:
    resumed_stages must not be claimed even if remeasured values happen
    to coincide with the snapshot."""
    records = tmp_path / "records.jsonl"
    # Pre-run snapshot: headline + a token from an OLD reset.
    records.write_text(
        json.dumps({"name": "_reset_token", "ok": True, "value": "old"})
        + "\n"
        + json.dumps({"name": "ft_headline", "ok": True,
                      "value": {"gflops": 30000.0, "strategy": "w"}})
        + "\n")
    bench = _load_bench()
    bench._PRE_VALUES = bench._read_records(str(records))[0]
    import io
    from contextlib import redirect_stdout

    # Same token at emit -> the headline stage genuinely resumed.
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = bench._emit(
            {"_reset_token": "old",
             "ft_headline": {"gflops": 30000.0, "strategy": "w"}}, {})
    payload = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert rc == 0
    assert payload["context"]["resumed_stages"] == 1

    bench2 = _load_bench()
    bench2._PRE_VALUES = bench2._read_records(str(records))[0]
    buf = io.StringIO()
    with redirect_stdout(buf):
        bench2._emit({"_reset_token": "NEW",  # fresh -> mid-run reset
                      "ft_headline": {"gflops": 30000.0, "strategy": "w"}},
                     {})
    payload = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert "resumed_stages" not in payload["context"], payload["context"]


def test_deadline_kill_salvages_streamed_partials(tmp_path):
    """The BENCH_r05 fix, end to end: the worker completes one context
    stage (records + streamed timeline), then hangs in the next until
    the supervisor's deadline kill. The artifact must be NON-NULL —
    best completed measurement promoted — marked ``context.partial``
    with the completed-stage list and the kill point's in-flight stage
    from the timeline."""
    proc = _run(_env(tmp_path, FT_SGEMM_BENCH_DEADLINE="8",
                     FT_SGEMM_BENCH_WORKER_MAX="3",
                     FT_SGEMM_BENCH_EXTEND_MAX="2",
                     FT_SGEMM_BENCH_FAKE_PARTIAL="25600.0"))
    payload = _payload(proc)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert payload["value"] == 25600.0
    ctx = payload["context"]
    assert ctx["partial"] is True
    assert ctx["strategy"] == "rowcol"
    assert "ft_rowcol" in ctx["completed_stages"]
    assert ctx["killed_at_stage"] == "ft_fused"
    assert "killed (" in ctx["errors"]["worker_rc"]
    # The streamed timeline is on disk next to the records, renderable
    # post hoc, and carries the supervisor's kill marker.
    tl_path = tmp_path / "records.jsonl.timeline.jsonl"
    assert tl_path.exists()
    assert ctx["timeline"] == tl_path.name
    bench = _load_bench()
    tlmod = bench._load_timeline_mod()
    summary = tlmod.summarize_timeline(tlmod.read_timeline(str(tl_path)))
    assert summary["killed_at_stage"] == "ft_fused"
    assert summary["kills"], "supervisor must write a kill marker"
    assert summary["stage_values"]["ft_rowcol"] == 25600.0


def test_timeline_only_salvage_recovers_lost_record(tmp_path):
    """A stage whose timeline end landed but whose records write was
    lost (or a records file from a dead fs) still yields a non-null
    artifact: the supervisor merges the timeline's streamed stage values
    into the emit."""
    records = tmp_path / "records.jsonl"
    records.write_text(json.dumps(
        {"name": "backend", "ok": True,
         "value": {"backend": "tpu", "device": "d",
                   "num_devices": 1}}) + "\n")
    bench = _load_bench()
    tlmod = bench._load_timeline_mod()
    tl = tlmod.TimelineRecorder(str(records) + ".timeline.jsonl")
    with tl.span("ft_rowcol", kind="stage") as info:
        info["value"] = 29100.0
    tl.close()
    # Deadline below MIN_ATTEMPT: emit from disk only, no worker runs.
    proc = _run(_env(tmp_path, FT_SGEMM_BENCH_DEADLINE="5",
                     FT_SGEMM_BENCH_MIN_ATTEMPT="99"))
    payload = _payload(proc)
    assert proc.returncode == 0
    assert payload["value"] == 29100.0
    assert payload["context"]["partial"] is True
    assert payload["context"]["strategy"] == "rowcol"
    assert "ft_rowcol" in payload["context"]["completed_stages"]


def test_smoke_mode_runs_both_encodes_on_cpu(tmp_path):
    """``--smoke``: the CI liveness check — one tiny size, both encode
    modes, valid JSON, rc 0 — must run without a TPU (the CPU interpret
    path) and without the supervisor machinery."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["FT_SGEMM_TUNER_CACHE"] = str(tmp_path / "tuner_cache.json")
    proc = subprocess.run([sys.executable, str(BENCH), "--smoke"], env=env,
                          capture_output=True, text=True, timeout=240)
    payload = _payload(proc)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert payload["metric"] == "bench_smoke"
    assert payload["value"] == 1
    modes = payload["context"]["encode_modes"]
    assert set(modes) == {"vpu", "mxu"}
    for mode, rec in modes.items():
        assert rec["corrected_ok"], (mode, rec)
        assert rec["detections"] > 0 and rec["uncorrectable"] == 0, (
            mode, rec)
    # Low-precision stages (ISSUE 7): one bf16-adaptive row and one int8
    # row — both new axes (threshold mode x dtype) exercised in CI.
    lp = payload["context"]["low_precision"]
    assert set(lp) == {"ft_rowcol[bf16-adaptive]", "ft_rowcol[int8]"}
    for name, rec in lp.items():
        assert rec["corrected_ok"], (name, rec)
        assert rec["detections"] > 0 and rec["uncorrectable"] == 0, (
            name, rec)
    # Their roofline rows judge against the STAGE dtype's ceiling.
    stage_dtypes = {s["name"]: s["dtype"]
                    for s in payload["context"]["run_report"]["stages"]}
    assert stage_dtypes["ft_rowcol[bf16-adaptive]"] == "bfloat16"
    assert stage_dtypes["ft_rowcol[int8]"] == "int8"


def test_encode_comparison_context_from_partial_records(tmp_path):
    """The VPU-vs-MXU comparison context assembles from whatever stage
    records landed — including a partial sweep killed mid-run (here the
    MXU weighted pair is missing entirely): the JSON stays valid and the
    pairs that exist are reported."""
    records = tmp_path / "records.jsonl"
    records.write_text(
        json.dumps({"name": "ft_headline", "ok": True,
                    "value": {"gflops": 30000.0, "strategy": "weighted"}})
        + "\n"
        + json.dumps({"name": "ft_rowcol", "ok": True, "value": 25600.0})
        + "\n"
        + json.dumps({"name": "ft_rowcol_mxu", "ok": True,
                      "value": 28100.0})
        + "\n")
    proc = _run(_env(tmp_path, FT_SGEMM_BENCH_DEADLINE="5",
                     FT_SGEMM_BENCH_MIN_ATTEMPT="99"))
    payload = _payload(proc)
    assert proc.returncode == 0
    cmp_ctx = payload["context"]["encode_comparison"]
    assert cmp_ctx["size"] == 4096
    assert cmp_ctx["rowcol"] == {"vpu": 25600.0, "mxu": 28100.0}
    # weighted pair: the ladder VPU number is present, the MXU (fused)
    # stage never landed — the half that exists is still reported.
    assert cmp_ctx["weighted"] == {"vpu": 30000.0}
    assert payload["context"]["abft_rowcol_mxu_gflops"] == 28100.0


def test_headline_rung_timeline_salvage(tmp_path):
    """Headline-first salvage at RUNG granularity: a deadline kill
    between ladder rungs leaves the completed rung's measurement only in
    the streamed timeline (under ``ft_headline[<label>]`` — the outer
    ft_headline record never landed). The emit must promote it."""
    records = tmp_path / "records.jsonl"
    records.write_text(json.dumps(
        {"name": "backend", "ok": True,
         "value": {"backend": "tpu", "device": "d",
                   "num_devices": 1}}) + "\n")
    bench = _load_bench()
    tlmod = bench._load_timeline_mod()
    tl = tlmod.TimelineRecorder(str(records) + ".timeline.jsonl")
    with tl.span("ft_headline", kind="stage"):
        with tl.span("ft_headline[weighted (deferred single-check "
                     "localization)]", kind="stage") as info:
            info["value"] = 24800.0
            info["compile_seconds"] = 300.0
            info["execute_seconds"] = 40.0
        # Next rung starts, never ends: the kill point.
        tl._write({"kind": "stage", "name": "ft_headline[rowcol]",
                   "phase": "start", "t": 12345.0})
    tl.close()
    proc = _run(_env(tmp_path, FT_SGEMM_BENCH_DEADLINE="5",
                     FT_SGEMM_BENCH_MIN_ATTEMPT="99"))
    payload = _payload(proc)
    assert proc.returncode == 0
    assert payload["value"] == 24800.0
    assert payload["context"]["partial"] is True
    assert payload["context"]["strategy"] == (
        "weighted (deferred single-check localization)")


def test_compile_cache_context_from_records(tmp_path):
    """The artifact context must carry the compile-cache triple — the
    enabled flag flattened, the reason NAMED (never swallowed), and the
    full stats dict — straight from the banked compile_cache record."""
    records = tmp_path / "records.jsonl"
    records.write_text(
        json.dumps({"name": "ft_headline", "ok": True,
                    "value": {"gflops": 30000.0, "strategy": "w"}}) + "\n"
        + json.dumps({"name": "compile_cache", "ok": True,
                      "value": {"enabled": False, "path": None,
                                "reason": "disabled by "
                                          "FT_SGEMM_COMPILE_CACHE=0",
                                "hits": 0, "misses": 0}}) + "\n")
    proc = _run(_env(tmp_path, FT_SGEMM_BENCH_DEADLINE="5",
                     FT_SGEMM_BENCH_MIN_ATTEMPT="99"))
    payload = _payload(proc)
    assert proc.returncode == 0
    ctx = payload["context"]
    assert ctx["compile_cache_enabled"] is False
    assert "FT_SGEMM_COMPILE_CACHE" in ctx["compile_cache_reason"]
    assert ctx["compile_cache"]["misses"] == 0


def test_double_smoke_warm_start(tmp_path):
    """The warm-start acceptance path, run locally exactly as CI runs
    it: two --smoke runs sharing one FT_SGEMM_COMPILE_CACHE dir. The
    second must report cache hits > 0, zero misses of the first run's
    entries, and a STRICTLY lower compile-wall fraction; both artifacts
    carry stage spans with a compile/execute split and wall fractions
    summing to <= 1."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["FT_SGEMM_TUNER_CACHE"] = str(tmp_path / "tuner_cache.json")
    env["FT_SGEMM_COMPILE_CACHE"] = str(tmp_path / "jaxcache")

    def smoke(tag):
        e = dict(env)
        e["FT_SGEMM_BENCH_TIMELINE"] = str(tmp_path / f"{tag}.tl.jsonl")
        proc = subprocess.run([sys.executable, str(BENCH), "--smoke"],
                              env=e, capture_output=True, text=True,
                              timeout=240)
        assert proc.returncode == 0, proc.stderr[-2000:]
        return _payload(proc)

    cold = smoke("cold")
    warm = smoke("warm")
    for artifact in (cold, warm):
        ctx = artifact["context"]
        assert ctx["compile_cache_enabled"] is True
        rr = ctx["run_report"]
        fractions = rr["wall"]["fractions"]
        assert sum(fractions.values()) <= 1.0 + 1e-9
        assert "other" in fractions
        # Every measured stage span carries the compile/execute split.
        stage_spans = [s for s in rr["timeline"]["spans"]
                       if s["kind"] == "stage"]
        assert stage_spans
        for s in stage_spans:
            assert isinstance(s.get("compile_seconds"), (int, float)), s
            assert isinstance(s.get("execute_seconds"), (int, float)), s
    assert cold["context"]["compile_cache"]["misses"] > 0
    assert cold["context"]["compile_cache"]["bytes_written"] > 0
    assert warm["context"]["compile_cache"]["hits"] > 0
    assert warm["context"]["compile_cache"]["misses"] == 0
    cold_frac = cold["context"]["run_report"]["wall"]["fractions"]["compile"]
    warm_frac = warm["context"]["run_report"]["wall"]["fractions"]["compile"]
    assert warm_frac < cold_frac, (cold_frac, warm_frac)


def test_headline_baseline_gate(tmp_path):
    """The committed 25.6 TFLOPS rowcol@4096 reference: a measured TPU
    headline regressing past tolerance fails the gate (exit 1), a
    matching-or-better one passes, and a CPU/smoke artifact (no headline
    stage) is incomparable — exit 0, never a failure."""
    from ft_sgemm_tpu import cli

    baseline = str(BENCH.parent / "BASELINE_HEADLINE.json")

    def artifact(payload):
        p = tmp_path / f"a{artifact.n}.json"
        artifact.n += 1
        p.write_text(json.dumps(payload) + "\n")
        return str(p)
    artifact.n = 0

    slow = artifact({"metric": "abft_kernel_huge_gflops_4096",
                     "value": 20000.0, "unit": "GFLOPS",
                     "vs_baseline": 4.994, "context": {}})
    good = artifact({"metric": "abft_kernel_huge_gflops_4096",
                     "value": 26100.0, "unit": "GFLOPS",
                     "vs_baseline": 6.517, "context": {}})
    nullv = artifact({"metric": "abft_kernel_huge_gflops_4096",
                      "value": None, "unit": "GFLOPS",
                      "vs_baseline": None,
                      "context": {"platform_used": "cpu"}})
    smoke = artifact({"metric": "bench_smoke", "value": 1, "unit": "ok",
                      "vs_baseline": None, "context": {"smoke": True}})
    assert cli.main(["cli", "bench-compare", baseline, slow]) == 1
    assert cli.main(["cli", "bench-compare", baseline, good]) == 0
    assert cli.main(["cli", "bench-compare", baseline, nullv]) == 0
    assert cli.main(["cli", "bench-compare", baseline, smoke]) == 0


def test_stage_budget_sizing():
    """Per-stage wall budget (graceful early-stop): 1.5x the largest
    completed stage, floored at the old 20 s guard, capped by
    FT_SGEMM_BENCH_STAGE_MAX."""
    bench = _load_bench()
    assert bench._stage_need(1.0, 300.0) == 20.0      # floor
    assert bench._stage_need(100.0, 300.0) == 150.0   # 1.5x estimate
    assert bench._stage_need(1000.0, 300.0) == 300.0  # cap


def test_code_version_paths_cover_worker_imports(tmp_path):
    """ADVICE r4: every repo-local module the worker imports must live
    under a CODE_VERSION_PATHS entry — a measurement-relevant module
    outside the keyed paths would let stale banked records be resumed
    after its code changed. The probe DERIVES the import set from
    bench.py's own AST (module level plus every function body, which is
    where worker_main's imports live), so a future worker import cannot
    silently fall out of the check, then asserts every repo-local module
    file in the resulting interpreter lands under a keyed path. Run in a
    subprocess so the closure is exactly bench's, not this session's."""
    repo = str(BENCH.parent)
    probe = tmp_path / "probe.py"
    probe.write_text(
        "import ast, importlib, json, os, sys\n"
        f"repo = {repo!r}\n"
        "sys.path.insert(0, repo)\n"
        "import importlib.util\n"
        "bench_path = os.path.join(repo, 'bench.py')\n"
        "spec = importlib.util.spec_from_file_location('bench', bench_path)\n"
        "bench = importlib.util.module_from_spec(spec)\n"
        "spec.loader.exec_module(bench)\n"
        "names = set()\n"
        "for node in ast.walk(ast.parse(open(bench_path).read())):\n"
        "    if isinstance(node, ast.Import):\n"
        "        names |= {a.name for a in node.names}\n"
        "    elif isinstance(node, ast.ImportFrom) and node.module \\\n"
        "            and node.level == 0:\n"
        "        names.add(node.module)\n"
        "failed = []\n"
        "for name in sorted(names):\n"
        "    try:\n"
        "        importlib.import_module(name)\n"
        "    except Exception as e:\n"
        "        failed.append([name, repr(e)])\n"
        "local = sorted({\n"
        "    os.path.realpath(f) for m in list(sys.modules.values())\n"
        "    if (f := getattr(m, '__file__', None))\n"
        "    and os.path.realpath(f).startswith(repo + os.sep)})\n"
        "print(json.dumps({'paths': local, 'failed': failed,\n"
        "                  'names': sorted(names),\n"
        "                  'keyed': bench.CODE_VERSION_PATHS}))\n")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run([sys.executable, str(probe)], env=env, text=True,
                         capture_output=True, timeout=240, cwd=repo)
    assert out.returncode == 0, out.stderr[-2000:]
    payload = json.loads(out.stdout.strip().splitlines()[-1])
    # The derivation must actually have seen the worker's package imports,
    # and every ft_sgemm_tpu import bench names must have succeeded (an
    # optional third-party dep may fail; a repo-local one may not).
    assert any(n.startswith("ft_sgemm_tpu") for n in payload["names"])
    repo_fails = [f for f in payload["failed"]
                  if f[0].startswith("ft_sgemm_tpu")]
    assert not repo_fails, repo_fails
    keyed = [os.path.join(repo, p) for p in payload["keyed"]]
    assert payload["paths"], "probe found no repo-local modules"
    for path in payload["paths"]:
        assert any(path == k or path.startswith(k + os.sep)
                   for k in keyed), (
            f"bench-reachable module {path} is outside "
            f"CODE_VERSION_PATHS {payload['keyed']}: its edits would not "
            "invalidate banked hardware records")
