"""bench.py resilience: the JSON line must survive every failure mode.

Round-1 postmortem: BENCH_r01.json recorded rc=1 with no JSON because a
transient axon backend-init failure escaped as a traceback. These tests pin
the guarantees the rework added: retries record errors instead of raising,
and main() emits a parseable JSON line even when the backend never comes up
or a measurement stage dies.
"""

import importlib.util
import json
import pathlib
import sys


def _load_bench():
    path = pathlib.Path(__file__).resolve().parent.parent / "bench.py"
    spec = importlib.util.spec_from_file_location("bench_under_test", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_retry_records_error_and_returns_none(monkeypatch):
    bench = _load_bench()
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    errors = {}
    calls = []

    def fails():
        calls.append(1)
        raise RuntimeError("Unable to initialize backend 'axon'")

    assert bench._retry("stage", fails, errors, attempts=3) is None
    assert len(calls) == 3
    assert "Unable to initialize backend" in errors["stage"]


def test_retry_succeeds_after_transient_failure(monkeypatch):
    bench = _load_bench()
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    errors = {}
    state = {"n": 0}

    def flaky():
        state["n"] += 1
        if state["n"] < 3:
            raise RuntimeError("HTTP 500 from tpu_compile_helper")
        return 42

    assert bench._retry("stage", flaky, errors, attempts=4) == 42
    assert errors == {}


def test_main_emits_json_when_backend_never_initializes(monkeypatch, capsys):
    bench = _load_bench()
    def never_up(errors):
        errors["backend_init"] = "boom"
        return None

    monkeypatch.setattr(bench, "_init_backend", never_up)
    rc = bench.main()
    out = capsys.readouterr().out.strip().splitlines()
    payload = json.loads(out[-1])  # last line is THE json line
    assert rc == 1
    assert payload["metric"] == "abft_kernel_huge_gflops_4096"
    assert payload["value"] is None
    assert payload["context"]["errors"]["backend_init"] == "boom"


def test_main_emits_json_when_measure_raises(monkeypatch, capsys):
    bench = _load_bench()
    monkeypatch.setattr(bench, "_init_backend",
                        lambda errors: {"backend": "fake", "device": "x",
                                        "num_devices": 1})

    def boom(context, errors):
        raise ValueError("factory exploded outside any retry wrapper")

    monkeypatch.setattr(bench, "_measure", boom)
    rc = bench.main()
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 1
    assert payload["value"] is None
    assert "factory exploded" in payload["context"]["errors"]["measure"]


def test_main_reports_headline_when_measure_succeeds(monkeypatch, capsys):
    bench = _load_bench()
    monkeypatch.setattr(bench, "_init_backend",
                        lambda errors: {"backend": "fake", "device": "x",
                                        "num_devices": 1})
    monkeypatch.setattr(bench, "_measure", lambda context, errors: 28510.0)
    rc = bench.main()
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert payload["value"] == 28510.0
    assert abs(payload["vs_baseline"] - 28510.0 / 4005.0) < 1e-3
