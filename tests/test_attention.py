"""Fault-tolerant attention: differential + injection + ring tests.

Beyond-reference capability (the reference has no attention; SURVEY.md §5),
tested to the same standard as the GEMM family: match an XLA oracle, and
with injection ON the output must STILL match (zero undetected corruption).
"""

import numpy as np
import pytest

from ft_sgemm_tpu import (
    InjectionSpec,
    attention_reference,
    ft_attention,
    make_ft_attention,
)
from ft_sgemm_tpu.ops.attention import (
    PV_SHAPE,
    QK_SHAPE,
    softmax_rowsum_residual,
)
from ft_sgemm_tpu.parallel import make_ring_mesh, ring_ft_attention
from ft_sgemm_tpu.utils import generate_random_matrix, verify_matrix


def _qkv(lq, lk, d, dv, seed=10):
    rng = np.random.default_rng(seed)
    return (
        generate_random_matrix(lq, d, rng=rng),
        generate_random_matrix(lk, d, rng=rng),
        generate_random_matrix(lk, dv, rng=rng),
    )


def test_clean_matches_oracle():
    q, k, v = _qkv(256, 384, 128, 128)
    res = ft_attention(q, k, v)
    want = np.asarray(attention_reference(q, k, v))
    np.testing.assert_allclose(np.asarray(res.out), want, rtol=1e-4,
                               atol=1e-5)
    assert int(res.detections) == 0
    assert int(res.softmax_flags) == 0


@pytest.mark.parametrize("strategy", ["rowcol", "weighted"])
def test_injected_faults_corrected_in_both_gemms(strategy):
    q, k, v = _qkv(256, 512, 128, 128, seed=3)
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    fn = make_ft_attention(strategy=strategy)
    res = fn(q, k, v, inj)
    want = np.asarray(attention_reference(q, k, v))
    # Corrected faults leave sub-0.01 residual noise in S that softmax
    # spreads across the row: judge with the framework's acceptance
    # tolerance (verify_matrix: fail iff abs>0.01 AND rel>0.01), like the
    # GEMM injection tests.
    ok, nbad, _ = verify_matrix(want, np.asarray(res.out), verbose=False)
    assert ok, f"{strategy}: {nbad} corrupted elements survived"

    # Both GEMMs saw the schedule: QK^T over d=128 (1 k-step at bk=128)
    # and PV over Lk=512 (1 k-step at bk=512), per tile.
    qk_tiles = -(-256 // QK_SHAPE.bm) * -(-512 // QK_SHAPE.bn)
    pv_tiles = -(-256 // PV_SHAPE.bm) * -(-128 // PV_SHAPE.bn)
    expected = (qk_tiles * inj.expected_faults(128, QK_SHAPE.bk)
                + pv_tiles * inj.expected_faults(512, PV_SHAPE.bk))
    assert int(res.detections) == expected
    assert int(res.softmax_flags) == 0


def test_odd_sizes_pad_correctly():
    q, k, v = _qkv(130, 300, 64, 96, seed=5)
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    res = ft_attention(q, k, v, inject=inj)
    want = np.asarray(attention_reference(q, k, v))
    ok, nbad, _ = verify_matrix(want, np.asarray(res.out), verbose=False)
    assert ok, f"odd sizes: {nbad} corrupted elements survived"
    assert int(res.detections) > 0


def test_bf16_input_mode():
    q, k, v = _qkv(256, 256, 128, 128, seed=7)
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    fn = make_ft_attention(in_dtype="bfloat16")
    res = fn(q, k, v, inj)
    want = np.asarray(attention_reference(q, k, v, in_dtype="bfloat16"))
    # bf16 input rounding flows through softmax; compare vs the bf16 oracle.
    np.testing.assert_allclose(np.asarray(res.out), want, rtol=2e-2,
                               atol=2e-3)
    assert int(res.detections) > 0


def test_causal_matches_oracle_and_corrects():
    q, k, v = _qkv(256, 256, 128, 128, seed=19)
    fn = make_ft_attention(causal=True)
    res = fn(q, k, v)
    want = np.asarray(attention_reference(q, k, v, causal=True))
    np.testing.assert_allclose(np.asarray(res.out), want, rtol=1e-4,
                               atol=1e-5)
    assert int(res.softmax_flags) == 0
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    res = fn(q, k, v, inj)
    ok, nbad, _ = verify_matrix(want, np.asarray(res.out), verbose=False)
    assert ok, f"causal: {nbad} corrupted elements survived"
    assert int(res.detections) > 0


def test_causal_shorter_query_end_aligned():
    # Decoding convention: L_q < L_k aligns at the end; the first query row
    # already sees lk - lq + 1 keys.
    q, k, v = _qkv(128, 384, 64, 64, seed=23)
    res = ft_attention(q, k, v, causal=True)
    want = np.asarray(attention_reference(q, k, v, causal=True))
    np.testing.assert_allclose(np.asarray(res.out), want, rtol=1e-4,
                               atol=1e-5)
    with pytest.raises(ValueError, match="causal"):
        ft_attention(k[:, :64], q[:, :64], v, causal=True)  # L_q > L_k


def test_ring_causal_matches_oracle():
    mesh = make_ring_mesh(8)
    q, k, v = _qkv(256, 512, 128, 128, seed=29)
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    want = np.asarray(attention_reference(q, k, v, causal=True))
    res = ring_ft_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(res.out), want, rtol=1e-4,
                               atol=1e-5)
    assert int(res.softmax_flags) == 0
    res = ring_ft_attention(q, k, v, mesh, causal=True, inject=inj)
    ok, nbad, _ = verify_matrix(want, np.asarray(res.out), verbose=False)
    assert ok, f"ring causal: {nbad} corrupted elements survived"
    assert int(res.detections) > 0


def test_multihead_via_vmap():
    """Multi-head use is jax.vmap over the single-head op (module
    docstring): pallas_call batches, detections count per head."""
    import jax

    rng = np.random.default_rng(17)
    h, l, d = 3, 128, 64
    q, k, v = (rng.uniform(-1, 1, (h, l, d)).astype(np.float32)
               for _ in range(3))
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    fn = make_ft_attention()
    res = jax.vmap(lambda q, k, v: fn(q, k, v, inj))(q, k, v)
    assert res.out.shape == (h, l, d)
    want = np.stack([np.asarray(attention_reference(q[i], k[i], v[i]))
                     for i in range(h)])
    for i in range(h):
        ok, nbad, _ = verify_matrix(want[i], np.asarray(res.out[i]),
                                    verbose=False)
        assert ok, f"head {i}: {nbad} corrupted elements survived"
    assert np.all(np.asarray(res.detections) > 0)


def test_softmax_invariant_flags_corrupted_rows():
    import jax.numpy as jnp

    p = jnp.full((8, 16), 1.0 / 16, jnp.float32)
    assert float(softmax_rowsum_residual(p)) < 1e-6
    p_bad = p.at[3, 0].add(0.5)  # normalization broken on row 3
    assert float(softmax_rowsum_residual(p_bad)) > 0.4


def test_ring_attention_matches_oracle():
    mesh = make_ring_mesh(8)
    q, k, v = _qkv(256, 512, 128, 128, seed=11)  # 32 q-rows, 64 kv per dev
    res = ring_ft_attention(q, k, v, mesh)
    want = np.asarray(attention_reference(q, k, v))
    np.testing.assert_allclose(np.asarray(res.out), want, rtol=1e-4,
                               atol=1e-5)
    assert int(res.detections) == 0
    assert int(res.softmax_flags) == 0


def test_ring_attention_corrects_injected_faults():
    mesh = make_ring_mesh(8)
    q, k, v = _qkv(256, 512, 128, 128, seed=13)
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    res = ring_ft_attention(q, k, v, mesh, inject=inj)
    want = np.asarray(attention_reference(q, k, v))
    ok, nbad, _ = verify_matrix(want, np.asarray(res.out), verbose=False)
    assert ok, f"ring: {nbad} corrupted elements survived"
    assert int(res.detections) > 0
    assert int(res.softmax_flags) == 0


def test_ring_attention_auto_threshold():
    """Adaptive thresholds compose with ring attention: each hop's GEMMs
    calibrate to their shard-local operands; tiny faults corrected."""
    from ft_sgemm_tpu.configs import KernelShape

    tile = KernelShape("t128", 128, 128, 128, (0,) * 7)
    q, k, v = _qkv(512, 512, 128, 128, seed=31)
    inj = InjectionSpec(enabled=True, every=1, magnitude=1.0)
    res = ring_ft_attention(q, k, v, make_ring_mesh(4), inject=inj,
                            threshold="auto", qk_shape=tile, pv_shape=tile)
    want = np.asarray(attention_reference(q, k, v))
    ok, nbad, _ = verify_matrix(want, np.asarray(res.out), verbose=False)
    assert ok, f"{nbad} tiny faults survived ring auto thresholds"
    assert int(res.detections) > 0
    assert int(res.uncorrectable) == 0
