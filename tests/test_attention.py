"""Fault-tolerant attention: differential + injection + ring tests.

Beyond-reference capability (the reference has no attention; SURVEY.md §5),
tested to the same standard as the GEMM family: match an XLA oracle, and
with injection ON the output must STILL match (zero undetected corruption).
"""

import numpy as np
import pytest

from ft_sgemm_tpu import (
    InjectionSpec,
    attention_reference,
    ft_attention,
    make_ft_attention,
)
from ft_sgemm_tpu.ops.attention import (
    PV_SHAPE,
    QK_SHAPE,
    softmax_rowsum_residual,
)
from ft_sgemm_tpu.parallel import (
    make_ring_ft_attention_diff, make_ring_mesh, ring_ft_attention)
from ft_sgemm_tpu.utils import generate_random_matrix, verify_matrix


def _qkv(lq, lk, d, dv, seed=10):
    rng = np.random.default_rng(seed)
    return (
        generate_random_matrix(lq, d, rng=rng),
        generate_random_matrix(lk, d, rng=rng),
        generate_random_matrix(lk, dv, rng=rng),
    )


def test_clean_matches_oracle():
    q, k, v = _qkv(256, 384, 128, 128)
    res = ft_attention(q, k, v)
    want = np.asarray(attention_reference(q, k, v))
    np.testing.assert_allclose(np.asarray(res.out), want, rtol=1e-4,
                               atol=1e-5)
    assert int(res.detections) == 0
    assert int(res.softmax_flags) == 0


@pytest.mark.parametrize("strategy", ["rowcol", "weighted"])
def test_injected_faults_corrected_in_both_gemms(strategy):
    q, k, v = _qkv(256, 512, 128, 128, seed=3)
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    fn = make_ft_attention(strategy=strategy)
    res = fn(q, k, v, inj)
    want = np.asarray(attention_reference(q, k, v))
    # Corrected faults leave sub-0.01 residual noise in S that softmax
    # spreads across the row: judge with the framework's acceptance
    # tolerance (verify_matrix: fail iff abs>0.01 AND rel>0.01), like the
    # GEMM injection tests.
    ok, nbad, _ = verify_matrix(want, np.asarray(res.out), verbose=False)
    assert ok, f"{strategy}: {nbad} corrupted elements survived"

    # Both GEMMs saw the schedule: QK^T over d=128 (1 k-step at bk=128)
    # and PV over Lk=512 (1 k-step at bk=512), per tile.
    qk_tiles = -(-256 // QK_SHAPE.bm) * -(-512 // QK_SHAPE.bn)
    pv_tiles = -(-256 // PV_SHAPE.bm) * -(-128 // PV_SHAPE.bn)
    expected = (qk_tiles * inj.expected_faults(128, QK_SHAPE.bk)
                + pv_tiles * inj.expected_faults(512, PV_SHAPE.bk))
    assert int(res.detections) == expected
    assert int(res.softmax_flags) == 0


def test_odd_sizes_pad_correctly():
    q, k, v = _qkv(130, 300, 64, 96, seed=5)
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    res = ft_attention(q, k, v, inject=inj)
    want = np.asarray(attention_reference(q, k, v))
    ok, nbad, _ = verify_matrix(want, np.asarray(res.out), verbose=False)
    assert ok, f"odd sizes: {nbad} corrupted elements survived"
    assert int(res.detections) > 0


def test_bf16_input_mode():
    q, k, v = _qkv(256, 256, 128, 128, seed=7)
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    fn = make_ft_attention(in_dtype="bfloat16")
    res = fn(q, k, v, inj)
    want = np.asarray(attention_reference(q, k, v, in_dtype="bfloat16"))
    # bf16 input rounding flows through softmax; compare vs the bf16 oracle.
    np.testing.assert_allclose(np.asarray(res.out), want, rtol=2e-2,
                               atol=2e-3)
    assert int(res.detections) > 0


def test_causal_matches_oracle_and_corrects():
    q, k, v = _qkv(256, 256, 128, 128, seed=19)
    fn = make_ft_attention(causal=True)
    res = fn(q, k, v)
    want = np.asarray(attention_reference(q, k, v, causal=True))
    np.testing.assert_allclose(np.asarray(res.out), want, rtol=1e-4,
                               atol=1e-5)
    assert int(res.softmax_flags) == 0
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    res = fn(q, k, v, inj)
    ok, nbad, _ = verify_matrix(want, np.asarray(res.out), verbose=False)
    assert ok, f"causal: {nbad} corrupted elements survived"
    assert int(res.detections) > 0


def test_causal_shorter_query_end_aligned():
    # Decoding convention: L_q < L_k aligns at the end; the first query row
    # already sees lk - lq + 1 keys.
    q, k, v = _qkv(128, 384, 64, 64, seed=23)
    res = ft_attention(q, k, v, causal=True)
    want = np.asarray(attention_reference(q, k, v, causal=True))
    np.testing.assert_allclose(np.asarray(res.out), want, rtol=1e-4,
                               atol=1e-5)
    with pytest.raises(ValueError, match="causal"):
        ft_attention(k[:, :64], q[:, :64], v, causal=True)  # L_q > L_k


def test_ring_causal_matches_oracle():
    mesh = make_ring_mesh(8)
    q, k, v = _qkv(256, 512, 128, 128, seed=29)
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    want = np.asarray(attention_reference(q, k, v, causal=True))
    res = ring_ft_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(res.out), want, rtol=1e-4,
                               atol=1e-5)
    assert int(res.softmax_flags) == 0
    res = ring_ft_attention(q, k, v, mesh, causal=True, inject=inj)
    ok, nbad, _ = verify_matrix(want, np.asarray(res.out), verbose=False)
    assert ok, f"ring causal: {nbad} corrupted elements survived"
    assert int(res.detections) > 0


def test_multihead_via_vmap():
    """Multi-head use is jax.vmap over the single-head op (module
    docstring): pallas_call batches, detections count per head."""
    import jax

    rng = np.random.default_rng(17)
    h, l, d = 3, 128, 64
    q, k, v = (rng.uniform(-1, 1, (h, l, d)).astype(np.float32)
               for _ in range(3))
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    fn = make_ft_attention()
    res = jax.vmap(lambda q, k, v: fn(q, k, v, inj))(q, k, v)
    assert res.out.shape == (h, l, d)
    want = np.stack([np.asarray(attention_reference(q[i], k[i], v[i]))
                     for i in range(h)])
    for i in range(h):
        ok, nbad, _ = verify_matrix(want[i], np.asarray(res.out[i]),
                                    verbose=False)
        assert ok, f"head {i}: {nbad} corrupted elements survived"
    assert np.all(np.asarray(res.detections) > 0)


def test_softmax_invariant_flags_corrupted_rows():
    import jax.numpy as jnp

    p = jnp.full((8, 16), 1.0 / 16, jnp.float32)
    assert float(softmax_rowsum_residual(p)) < 1e-6
    p_bad = p.at[3, 0].add(0.5)  # normalization broken on row 3
    assert float(softmax_rowsum_residual(p_bad)) > 0.4


@pytest.mark.parametrize("stage", ["exp", "denom", "post"])
def test_softmax_stage_faults_flagged(stage):
    """VERDICT r3 item 5's done criterion: a fault injected into the
    softmax/exp stage (NOT the GEMMs) is flagged. 'exp' corrupts e before
    the denominator — renormalization launders it past the rowsum
    invariant, so only the sampled dual recompute can see it (row 0 is
    always in the static-stride sample); 'denom' and 'post' break the
    normalization invariant directly."""
    q, k, v = _qkv(256, 256, 128, 128, seed=14)
    att = make_ft_attention(softmax_fault=(stage, 0, 5, 30.0))
    res = att(q, k, v)
    assert int(res.softmax_flags) > 0, f"{stage}-stage fault not flagged"
    assert int(res.detections) == 0, "GEMMs saw no injection"
    # Clean build on the same inputs: zero flags (no false positives).
    clean = make_ft_attention()(q, k, v)
    assert int(clean.softmax_flags) == 0
    want = np.asarray(attention_reference(q, k, v))
    np.testing.assert_allclose(np.asarray(clean.out), want, rtol=1e-4,
                               atol=1e-5)


def test_softmax_exp_fault_outside_sample_documents_coverage():
    """The dual recompute's coverage is SAMPLED: an exp-stage fault on an
    unsampled row is laundered by renormalization and passes unflagged —
    the documented residual exposure (GEMM checksums stay full-coverage;
    softmax redundancy is bought row-by-row). This test pins that the
    claim in the module docstring is exact, not optimistic."""
    q, k, v = _qkv(256, 256, 128, 128, seed=15)
    # 256 rows / 16 recheck rows -> stride 16: row 7 is unsampled.
    att = make_ft_attention(softmax_fault=("exp", 7, 5, 30.0))
    res = att(q, k, v)
    assert int(res.softmax_flags) == 0, (
        "unsampled exp fault should be invisible (if this fires, coverage "
        "improved — update the docs, not the check)")
    # ...and full-coverage mode (one recheck row per row) catches it.
    att_full = make_ft_attention(softmax_fault=("exp", 7, 5, 30.0),
                                 softmax_recheck_rows=256)
    assert int(att_full(q, k, v).softmax_flags) > 0


def test_softmax_checks_active_in_diff_path():
    """The decomposed checked softmax guards the differentiable factory
    too (same shared forward)."""
    import jax
    import jax.numpy as jnp

    from ft_sgemm_tpu import make_ft_attention_diff

    q, k, v = _qkv(256, 256, 128, 128, seed=16)
    att = make_ft_attention_diff(softmax_fault=("denom", 3, 0, 30.0),
                                 with_counts=True)
    res = att(q, k, v)
    assert int(res.softmax_flags) > 0
    # Gradients still flow (the checks are detect-only side outputs).
    g = jax.grad(lambda q: jnp.sum(att(q, k, v).out))(jnp.asarray(q))
    assert np.isfinite(np.asarray(g)).all()


def test_ring_attention_matches_oracle():
    mesh = make_ring_mesh(8)
    q, k, v = _qkv(256, 512, 128, 128, seed=11)  # 32 q-rows, 64 kv per dev
    res = ring_ft_attention(q, k, v, mesh)
    want = np.asarray(attention_reference(q, k, v))
    np.testing.assert_allclose(np.asarray(res.out), want, rtol=1e-4,
                               atol=1e-5)
    assert int(res.detections) == 0
    assert int(res.softmax_flags) == 0


def test_ring_attention_corrects_injected_faults():
    mesh = make_ring_mesh(8)
    q, k, v = _qkv(256, 512, 128, 128, seed=13)
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    res = ring_ft_attention(q, k, v, mesh, inject=inj)
    want = np.asarray(attention_reference(q, k, v))
    ok, nbad, _ = verify_matrix(want, np.asarray(res.out), verbose=False)
    assert ok, f"ring: {nbad} corrupted elements survived"
    assert int(res.detections) > 0
    assert int(res.softmax_flags) == 0


def test_ring_attention_auto_threshold():
    """Adaptive thresholds compose with ring attention: each hop's GEMMs
    calibrate to their shard-local operands; tiny faults corrected."""
    from ft_sgemm_tpu.configs import KernelShape

    tile = KernelShape("t128", 128, 128, 128, (0,) * 7)
    q, k, v = _qkv(512, 512, 128, 128, seed=31)
    inj = InjectionSpec(enabled=True, every=1, magnitude=1.0)
    res = ring_ft_attention(q, k, v, make_ring_mesh(4), inject=inj,
                            threshold="auto", qk_shape=tile, pv_shape=tile)
    want = np.asarray(attention_reference(q, k, v))
    ok, nbad, _ = verify_matrix(want, np.asarray(res.out), verbose=False)
    assert ok, f"{nbad} tiny faults survived ring auto thresholds"
    assert int(res.detections) > 0
    assert int(res.uncorrectable) == 0


# ---------------------------------------------------------------------------
# Differentiable ring attention (VERDICT r3 item 7)
# ---------------------------------------------------------------------------

def _ring_grad_pair(att, q, k, v, ref_kwargs):
    """Gradients through the ring path and the plain-XLA oracle."""
    import jax
    import jax.numpy as jnp

    def loss_ring(q, k, v):
        out = att(q, k, v)
        out = out.out if hasattr(out, "out") else out
        return jnp.sum(jnp.tanh(out))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.tanh(attention_reference(q, k, v, **ref_kwargs)))

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(
        *(jnp.asarray(x) for x in (q, k, v)))
    return g_ring, g_ref


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_diff_grads_match_oracle(causal):
    """The long-context path can TRAIN: custom-vjp ring attention on an
    8-device mesh, gradients vs the single-device XLA oracle — clean run,
    all backward products computed by a second ring pass with dK/dV
    rotating home."""
    mesh = make_ring_mesh(8)
    q, k, v = _qkv(256, 512, 128, 128, seed=21)
    att = make_ring_ft_attention_diff(mesh, causal=causal)
    g_ring, g_ref = _ring_grad_pair(att, q, k, v, {"causal": causal})
    for got, want, name in zip(g_ring, g_ref, ("dQ", "dK", "dV")):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-5,
                                   err_msg=f"ring {name} (causal={causal})")


def test_ring_attention_diff_grads_with_injection():
    """Injection ON in all forward and backward ring GEMMs: corrected
    in-kernel, gradients still match the clean oracle."""
    mesh = make_ring_mesh(4)
    q, k, v = _qkv(256, 512, 128, 128, seed=22)
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    att = make_ring_ft_attention_diff(mesh, inject=inj, inject_bwd=inj,
                                      with_counts=True)
    res = att(q, k, v)
    assert int(res.detections) > 0
    g_ring, g_ref = _ring_grad_pair(att, q, k, v, {})
    for got, want, name in zip(g_ring, g_ref, ("dQ", "dK", "dV")):
        ok, nbad, _ = verify_matrix(np.asarray(want), np.asarray(got),
                                    verbose=False)
        assert ok, f"ring {name}: {nbad} corrupted elements survived"


def test_ring_attention_diff_bwd_sink():
    """Backward ring GEMM counts ride the gradient side-channel: rotating
    injection -> detections reported, psum'd over the ring; clean -> 0."""
    import jax
    import jax.numpy as jnp

    mesh = make_ring_mesh(4)
    q, k, v = _qkv(256, 512, 128, 128, seed=23)

    def sink_grad(att):
        def loss(q, k, v, sink):
            return jnp.sum(jnp.tanh(att(q, k, v, sink)))

        return jax.grad(loss, argnums=3)(q, k, v, jnp.zeros(2))

    clean = sink_grad(make_ring_ft_attention_diff(mesh,
                                                  with_bwd_counts=True))
    assert float(clean[0]) == 0.0 and float(clean[1]) == 0.0

    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    rot = sink_grad(make_ring_ft_attention_diff(mesh, inject_bwd=inj,
                                                with_bwd_counts=True))
    assert float(rot[0]) > 0
    assert float(rot[1]) == 0.0


def test_ring_attention_diff_bf16_in_dtype_keeps_primal_dtype():
    """in_dtype='bfloat16' composes with the diff ring path: cotangents
    come back in the PRIMAL dtype (f32), not in_dtype (residuals keep the
    caller's arrays, like the single-device factory)."""
    import jax
    import jax.numpy as jnp

    mesh = make_ring_mesh(4)
    q, k, v = _qkv(128, 256, 128, 128, seed=24)
    att = make_ring_ft_attention_diff(mesh, in_dtype="bfloat16")
    g = jax.grad(lambda q, k, v: jnp.sum(jnp.tanh(att(q, k, v))),
                 argnums=(0, 1, 2))(*(jnp.asarray(x) for x in (q, k, v)))
    for arr, name in zip(g, ("dQ", "dK", "dV")):
        assert arr.dtype == jnp.float32, (name, arr.dtype)
        assert np.isfinite(np.asarray(arr)).all(), name


def test_ring_diff_recompute_keeps_forward_threshold(monkeypatch):
    """The backward ring's probability-recompute kernel mirrors the
    FORWARD QK product (activation-scale operands), so it must be built
    with `threshold`, not `bwd_threshold` — a cotangent-tight backward
    threshold there would false-positive on clean activation-scale
    checksum noise and trip the re-run gate on fault-free runs."""
    import ft_sgemm_tpu.parallel.ring_attention as ra

    calls = []
    orig = ra.make_ft_sgemm

    def spy(shape, **kw):
        calls.append(kw.get("threshold"))
        return orig(shape, **kw)

    monkeypatch.setattr(ra, "make_ft_sgemm", spy)
    make_ring_ft_attention_diff(make_ring_mesh(4), threshold=9500.0,
                                bwd_threshold=1.0)
    # Factory-time construction order: recompute qk_b, then the gradient
    # kernels b_long, b_short.
    assert calls[0] == 9500.0, (
        "recompute kernel must keep the forward threshold")
    assert calls[1:] == [1.0, 1.0], (
        "gradient kernels must take bwd_threshold")
