"""Mesh-sharded FT-SGEMM over 8 virtual CPU devices."""

import jax
import numpy as np
import pytest

from ft_sgemm_tpu import InjectionSpec, sgemm_reference
from ft_sgemm_tpu.configs import KernelShape
from ft_sgemm_tpu.parallel import make_mesh, sharded_ft_sgemm, sharded_sgemm
from ft_sgemm_tpu.utils import generate_random_matrix, verify_matrix

ALPHA, BETA = 1.0, -1.5
TILE = KernelShape("t128", 128, 128, 128, (0,) * 7)


def _inputs(m, n, k, seed=10):
    rng = np.random.default_rng(seed)
    return (
        generate_random_matrix(m, k, rng=rng),
        generate_random_matrix(n, k, rng=rng),
        generate_random_matrix(m, n, rng=rng),
    )


def test_make_mesh_factorizes():
    mesh = make_mesh(8)
    assert mesh.shape["x"] * mesh.shape["y"] == 8
    assert mesh.shape["x"] == 2 and mesh.shape["y"] == 4


def test_sharded_sgemm_matches_oracle():
    mesh = make_mesh(8)  # 2 x 4
    m, n, k = 256, 128, 512  # M/2 = 128, K/4 = 128 per device
    a, b, c = _inputs(m, n, k)
    got = np.asarray(sharded_sgemm(a, b, c, mesh, TILE, alpha=ALPHA, beta=BETA))
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_sharded_ft_clean_matches_oracle():
    mesh = make_mesh(8)
    m, n, k = 256, 128, 512
    a, b, c = _inputs(m, n, k, seed=3)
    res = sharded_ft_sgemm(a, b, c, mesh, TILE, alpha=ALPHA, beta=BETA)
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok, f"{nbad} bad"
    assert int(res.num_detected) == 0


@pytest.mark.parametrize("strategy", ["rowcol", "weighted"])
def test_sharded_ft_corrects_injected_faults_before_psum(strategy):
    # "weighted" at default cadence routes to the precomputed-checksum
    # kernel — exercising the XLA expectation dots under shard_map.
    mesh = make_mesh(8)
    m, n, k = 256, 128, 512
    a, b, c = _inputs(m, n, k, seed=4)
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    res = sharded_ft_sgemm(a, b, c, mesh, TILE, alpha=ALPHA, beta=BETA,
                           inject=inj, strategy=strategy)
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok, f"{nbad} corrupted elements survived the cross-chip psum"
    # Each of the 8 devices injects into its own K-partial: local k-steps =
    # 512/4/128 = 1 per device; grid per device: (128/128)x(128/128) = 1.
    assert int(res.num_detected) == 8


def test_sharded_ft_scatter_output_matches_psum_path():
    # reduce-scatter layout: same math, output lands sharded P("x", "y").
    mesh = make_mesh(8)  # 2 x 4
    m, n, k = 256, 512, 512  # N/4 = 128 per device along y
    a, b, c = _inputs(m, n, k, seed=7)
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    scat = sharded_ft_sgemm(a, b, c, mesh, TILE, alpha=ALPHA, beta=BETA,
                            inject=inj, scatter_output=True)
    full = sharded_ft_sgemm(a, b, c, mesh, TILE, alpha=ALPHA, beta=BETA,
                            inject=inj)
    np.testing.assert_allclose(np.asarray(scat.c), np.asarray(full.c),
                               rtol=1e-5, atol=1e-5)
    assert int(scat.num_detected) == int(full.num_detected) > 0
    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA))
    ok, nbad, _ = verify_matrix(want, np.asarray(scat.c), verbose=False)
    assert ok, f"{nbad} corrupted elements survived the reduce-scatter"


def test_sharded_scatter_rejects_indivisible_n():
    mesh = make_mesh(8)  # y = 4
    a, b, c = _inputs(256, 130, 512)  # 130 % 4 != 0
    with pytest.raises(ValueError, match="divide evenly"):
        sharded_ft_sgemm(a, b, c, mesh, TILE, scatter_output=True)


def test_sharded_bf16_matches_rounded_oracle():
    from conftest import bf16_rounded_oracle

    mesh = make_mesh(8)
    m, n, k = 256, 128, 512
    a, b, c = _inputs(m, n, k, seed=8)
    got = np.asarray(sharded_sgemm(a, b, c, mesh, TILE, alpha=ALPHA,
                                   beta=BETA, in_dtype="bfloat16"))
    want = bf16_rounded_oracle(a, b, c, ALPHA, BETA)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_sharded_rejects_indivisible():
    mesh = make_mesh(8)
    a, b, c = _inputs(301, 128, 512)  # 301 % mesh_x(2) != 0
    with pytest.raises(ValueError, match="divide evenly"):
        sharded_sgemm(a, b, c, mesh, TILE)


def test_dryrun_multichip_entrypoint():
    import __graft_entry__ as g

    g.dryrun_multichip(8)


def test_entry_compiles_single_device():
    import __graft_entry__ as g

    fn, args = g.entry()
    out, det = jax.jit(fn)(*args)
    assert out.shape == (512, 512)
    assert int(det.sum()) > 0
