"""Chaos campaign plane (ISSUE 19): declarative fault models with
seeded determinism, the measured coverage matrix over real workloads,
clean-twin zero-false-positive pins, coverage round-trip + ledger
ingest, MTBF-driven policy monotonicity, and the trend gate failing a
seeded coverage regression."""

import io
import json
import random
import subprocess
import sys

import numpy as np
import pytest

from ft_sgemm_tpu import contracts
from ft_sgemm_tpu.chaos import (
    FAULT_MODELS,
    MODELS,
    WORKLOADS,
    FaultModel,
    draw_episode,
)
from ft_sgemm_tpu.chaos import policy
from ft_sgemm_tpu.cli import chaos_verdict, main as cli_main
from ft_sgemm_tpu.perf import ledger

# ---------------------------------------------------------------------------
# Declarations and seeded determinism
# ---------------------------------------------------------------------------


def test_fault_models_mirror_contracts():
    """The runtime spelling, the contracts declaration, and the event
    axis must agree (the lint axis-drift pass enforces the same)."""
    from ft_sgemm_tpu.telemetry.events import AXIS_LABELS

    assert FAULT_MODELS == contracts.FAULT_MODELS
    assert tuple(MODELS) == FAULT_MODELS
    assert set(AXIS_LABELS["fault_model"]) == set(FAULT_MODELS)


def test_model_specs_validate():
    for name, m in MODELS.items():
        assert m.name == name
        assert m.mtbf_seconds() > 0
        assert m.workloads and all(w in WORKLOADS for w in m.workloads)
    with pytest.raises(ValueError):
        FaultModel(name="not_a_model", site="x", actuator="y",
                   workloads=("train_step",),
                   magnitude=("absolute", 1.0, 2.0),
                   temporal="transient", rate_per_hour=1.0,
                   correctable=False, description="")
    with pytest.raises(ValueError):
        FaultModel(name="bit_flip", site="x", actuator="y",
                   workloads=("nope",),
                   magnitude=("absolute", 1.0, 2.0),
                   temporal="transient", rate_per_hour=1.0,
                   correctable=False, description="")


def test_draw_episode_deterministic_under_seed():
    """Same seed, same episode schedule — a coverage regression is a
    code change, never draw noise."""
    for name, model in MODELS.items():
        a = [draw_episode(model, random.Random(7)) for _ in range(4)]
        b = [draw_episode(model, random.Random(7)) for _ in range(4)]
        assert a == b, name
    # Different seeds move at least the continuous magnitude draw.
    m = MODELS["bit_flip"]
    assert draw_episode(m, random.Random(1)) \
        != draw_episode(m, random.Random(2))


def test_campaign_cell_stream_is_process_stable():
    """The per-cell stream seeds from a STRING (sha512-derived), not
    hash() of a tuple — identical across interpreter runs regardless of
    PYTHONHASHSEED."""
    a = random.Random("10:bit_flip:train_step").random()
    b = random.Random("10:bit_flip:train_step").random()
    assert a == b
    assert random.Random("11:bit_flip:train_step").random() != a


# ---------------------------------------------------------------------------
# The measured coverage matrix (one shared campaign run)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def coverage_doc():
    """One campaign over three models whose tiers span the stack:
    bit_flip (in-kernel device tier), multi_device_burst (staged
    host/global tier on the 8-vdev mesh), kv_rot (stored-state
    kv_page tier), plus throughput_sag (health tier, jax-free)."""
    from ft_sgemm_tpu.chaos.campaign import ChaosCampaign

    return ChaosCampaign(
        models=("bit_flip", "multi_device_burst", "kv_rot",
                "throughput_sag"),
        workloads=("train_step", "block_serve", "pool_evict"),
        episodes=2, clean_episodes=1, seed=10).run()


def test_coverage_matrix_non_null(coverage_doc):
    chaos = coverage_doc["context"]["chaos"]
    assert set(chaos["models"]) == {"bit_flip", "multi_device_burst",
                                    "kv_rot", "throughput_sag"}
    for name, entry in chaos["models"].items():
        roll = entry["rollup"]
        assert roll["detection_rate"] == 1.0, name
        assert roll["p95_detection_latency_seconds"] > 0, name
        assert roll["mttr_seconds"] > 0, name
        assert roll["incorrect_results"] == 0, name
        for cell in entry["cells"].values():
            assert cell["faults_injected"] == 2
            assert cell["detection_latency_seconds"] is not None
    assert coverage_doc["value"] == 1.0
    assert chaos_verdict(coverage_doc)


def test_tier_of_detection_per_model(coverage_doc):
    """Each model is caught where its site says it must be: the
    transient upset in-kernel (device), the correlated sub-threshold
    burst only at the staged host/global reduce, KV rot at the page
    checksum, health sag at the pool."""
    models = coverage_doc["context"]["chaos"]["models"]
    assert set(models["bit_flip"]["rollup"]["tier_of_detection"]) \
        == {"device"}
    burst_tiers = set(
        models["multi_device_burst"]["rollup"]["tier_of_detection"])
    assert burst_tiers and burst_tiers <= {"host", "global"}
    assert set(models["kv_rot"]["rollup"]["tier_of_detection"]) \
        == {"kv_page"}
    assert set(models["throughput_sag"]["rollup"]["tier_of_detection"]) \
        == {"health"}


def test_clean_twins_zero_false_positives(coverage_doc):
    """Every cell ran a clean twin; none may have alarmed."""
    for name, entry in coverage_doc["context"]["chaos"]["models"].items():
        for workload, cell in entry["cells"].items():
            assert cell["clean_episodes"] >= 1, (name, workload)
            assert cell["false_positives"] == 0, (name, workload)
            assert cell["false_positive_rate"] == 0.0, (name, workload)


def test_correctable_models_correct_not_just_detect(coverage_doc):
    models = coverage_doc["context"]["chaos"]["models"]
    for name in ("bit_flip", "kv_rot"):
        assert models[name]["spec"]["correctable"]
        assert models[name]["rollup"]["correction_rate"] == 1.0, name


def test_coverage_roundtrip_and_ledger_ingest(coverage_doc, tmp_path):
    """COVERAGE.json is artifact-shaped: it survives a JSON round trip
    and the ledger ingests it as kind=chaos with per-model chaos.*
    measurements (which perf/trend.py then gates for free)."""
    p = tmp_path / "COVERAGE.json"
    p.write_text(json.dumps(coverage_doc))
    doc = json.loads(p.read_text())
    assert doc == coverage_doc

    entry = ledger.ingest(doc, run_id="r-chaos")
    assert entry["kind"] == "chaos"
    meas = entry["measurements"]
    assert meas["chaos.bit_flip.detection_rate"] == \
        {"value": 1.0, "higher_is_better": True}
    assert meas["chaos.kv_rot.mttr_seconds"]["higher_is_better"] is False
    assert meas["chaos.multi_device_burst.false_positive_rate"] == \
        {"value": 0.0, "higher_is_better": False}
    # Categorical facts ride the entry body, not the trend plane.
    body = entry["chaos"]["multi_device_burst"]
    assert set(body["tier_of_detection"]) <= {"host", "global"}
    assert body["policy"]["tier_config"] == "tiered"
    # ingest never raises on malformed chaos sections.
    assert ledger.ingest({"metric": "chaos_coverage", "value": 1.0,
                          "context": {"chaos": {"models": "bogus"}}},
                         run_id="r-bad")["kind"] == "chaos"


def test_policy_recommendations_differ_measurably(coverage_doc):
    """ISSUE 19 acceptance: the picker recommends measurably different
    (cadence, threshold) pairs across models."""
    models = coverage_doc["context"]["chaos"]["models"]
    picks = {name: (e["policy"]["check_every"],
                    e["policy"]["threshold_mode"])
             for name, e in models.items()}
    assert len(set(picks.values())) >= 2, picks
    # At a fixed measured window the 60s-MTBF transient checks denser
    # than the 7200s sag (the campaign windows differ per workload, so
    # pin the MTBF→cadence ordering at window=1s).
    assert policy.recommend_cadence(MODELS["bit_flip"].mtbf_seconds(),
                                    1.0) \
        < policy.recommend_cadence(
            MODELS["throughput_sag"].mtbf_seconds(), 1.0)


# ---------------------------------------------------------------------------
# Policy derivation (pure, jax-free)
# ---------------------------------------------------------------------------


def test_cadence_monotone_in_mtbf():
    cadences = [policy.recommend_cadence(mtbf, 1.0)
                for mtbf in (1.0, 60.0, 600.0, 3600.0, 86400.0)]
    assert cadences == sorted(cadences)
    assert cadences[0] < cadences[-1]
    assert all(policy.MIN_CHECK_EVERY <= c <= policy.MAX_CHECK_EVERY
               for c in cadences)
    assert policy.recommend_cadence(0.0) == policy.MIN_CHECK_EVERY
    assert policy.recommend_cadence(1e12) == policy.MAX_CHECK_EVERY


def test_recommend_threshold_tier_and_evict_branches():
    spec = MODELS["residual_drift"].to_dict()
    rollup = {"detection_rate": 1.0, "static_detection_rate": 0.0,
              "p95_detection_latency_seconds": 0.01,
              "mttr_seconds": 0.02, "tier_of_detection": {"device": 2}}
    rec = policy.recommend(spec, rollup)
    assert rec["threshold_mode"] == "adaptive"
    assert rec["tier_config"] == "device"
    assert rec["evict"] is False
    assert "adaptive" in rec["justification"]

    spec = MODELS["multi_device_burst"].to_dict()
    rec = policy.recommend(spec, {"detection_rate": 1.0,
                                  "tier_of_detection": {"host": 2}})
    assert rec["threshold_mode"] == "static"
    assert rec["tier_config"] == "tiered"

    spec = MODELS["stuck_device"].to_dict()
    rec = policy.recommend(spec, {"detection_rate": 1.0})
    assert rec["evict"] is True


def test_chaos_verdict_predicate():
    def doc(**rollup):
        return {"context": {"chaos": {"models": {"m": {
            "spec": {"correctable": True},
            "rollup": dict({"detection_rate": 1.0,
                            "incorrect_results": 0,
                            "false_positive_rate": 0.0}, **rollup)}}}}}

    assert chaos_verdict(doc())
    assert not chaos_verdict(doc(detection_rate=0.5))
    assert not chaos_verdict(doc(detection_rate=None))
    assert not chaos_verdict(doc(incorrect_results=1))
    assert not chaos_verdict(doc(false_positive_rate=0.5))
    assert not chaos_verdict({"context": {}})


# ---------------------------------------------------------------------------
# Trend gate on seeded coverage regression
# ---------------------------------------------------------------------------


def _chaos_artifact(det):
    return {"metric": "chaos_coverage", "value": det, "unit": "rate",
            "vs_baseline": None,
            "context": {"platform_used": "cpu", "device_kind": "cpu",
                        "chaos": {"workloads": ["train_step"],
                                  "models": {"bit_flip": {
                            "spec": {"correctable": True},
                            "mtbf_seconds": 60.0,
                            "rollup": {"detection_rate": det},
                            "policy": {},
                            "cells": {"train_step": {
                                "detection_rate": det,
                                "correction_rate": det,
                                "detection_latency_seconds":
                                    {"p95": 0.01},
                                "mttr_seconds": 0.02,
                                "false_positive_rate": 0.0,
                                "goodput_retention": 0.97,
                                "tier_of_detection":
                                    {"device": 2}}}}}}}}


def test_trend_gate_fails_on_coverage_regression(tmp_path, capsys):
    """ISSUE 19 acceptance: a seeded detection-rate regression trips
    `cli trend --gate` exit 1."""
    path = str(tmp_path / "led.jsonl")
    for i in range(4):
        ledger.append(path, ledger.ingest(_chaos_artifact(1.0),
                                          run_id=f"r{i}"))
    ledger.append(path, ledger.ingest(_chaos_artifact(0.5),
                                      run_id="regressed"))
    rc = cli_main(["cli", "trend", path, "--gate"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "chaos.bit_flip.detection_rate" in out
    assert "regression" in out


def test_trend_gate_passes_on_stable_coverage(tmp_path, capsys):
    path = str(tmp_path / "led.jsonl")
    for i in range(5):
        ledger.append(path, ledger.ingest(_chaos_artifact(1.0),
                                          run_id=f"r{i}"))
    assert cli_main(["cli", "trend", path, "--gate"]) == 0
    capsys.readouterr()


# ---------------------------------------------------------------------------
# Detection-latency histogram: live export + single-stats-path rebuild
# ---------------------------------------------------------------------------


def test_detection_latency_histogram_rebuild(tmp_path):
    """`registry_from_events` rebuilds fault_detection_latency_seconds
    from the JSONL log with the SAME stats the live registry observed —
    the serve_latency_seconds single-stats-path discipline."""
    from ft_sgemm_tpu import telemetry
    from ft_sgemm_tpu.cli import run_telemetry_summary
    from ft_sgemm_tpu.telemetry import read_events, registry_from_events
    from ft_sgemm_tpu.telemetry.registry import (
        LATENCY_BUCKETS, MetricsRegistry, to_prometheus)

    log = tmp_path / "chaos_events.jsonl"
    live = MetricsRegistry()
    telemetry.configure(log, registry=live, log_clean=True)
    try:
        for lat in (0.002, 0.25):
            live.histogram("fault_detection_latency_seconds",
                           buckets=LATENCY_BUCKETS,
                           fault_model="bit_flip").observe(lat)
            telemetry.record_step_event(
                "alert", op="chaos",
                extra={"fault_model": "bit_flip",
                       "workload": "train_step",
                       "detection_latency_seconds": lat})
        # A chaos event WITHOUT a latency must not feed the histogram.
        telemetry.record_step_event(
            "alert", op="chaos", extra={"fault_model": "bit_flip"})
    finally:
        telemetry.disable()

    rebuilt = registry_from_events(read_events(log))

    def family(reg):
        return [s for s in reg.collect()
                if s["name"] == "fault_detection_latency_seconds"]

    got, want = family(rebuilt), family(live)
    assert want and got
    assert got[0]["labels"] == {"fault_model": "bit_flip"}
    assert got[0]["value"] == want[0]["value"]
    prom = to_prometheus(rebuilt.collect())
    assert "fault_detection_latency_seconds_bucket" in prom
    assert 'fault_model="bit_flip"' in prom
    # The CLI prom exporter is the same path.
    buf = io.StringIO()
    assert run_telemetry_summary(str(log), out=buf, fmt="prom") == 0
    assert "fault_detection_latency_seconds_bucket" in buf.getvalue()


def test_top_tolerates_chaos_gauge_families(capsys):
    """`cli top` scrapes by name: the new chaos_* / coverage_* families
    (and the latency histogram) must render-through without crashing."""
    from ft_sgemm_tpu.cli import run_top
    from ft_sgemm_tpu.telemetry.monitor import start_monitor
    from ft_sgemm_tpu.telemetry.registry import (
        LATENCY_BUCKETS, MetricsRegistry)

    reg = MetricsRegistry()
    reg.counter("chaos_episodes", fault_model="bit_flip",
                workload="train_step").inc(3)
    reg.gauge("coverage_detection_rate", fault_model="bit_flip").set(1.0)
    reg.histogram("fault_detection_latency_seconds",
                  buckets=LATENCY_BUCKETS,
                  fault_model="bit_flip").observe(0.01)
    mon, server = start_monitor(0, registry=reg, attach=False)
    try:
        buf = io.StringIO()
        assert run_top(server.url, out=buf, interval=0.01,
                       iterations=1) == 0
        assert "ft-sgemm top" in buf.getvalue()
    finally:
        server.close()


# ---------------------------------------------------------------------------
# Front ends: cli chaos / cli coverage / summarize_bench
# ---------------------------------------------------------------------------


def test_cli_chaos_smoke_pool_only(tmp_path, capsys):
    """The cheap jax-free slice of `cli chaos --smoke`: pool-tier model
    only, artifact + COVERAGE.json + chaos timeline spans emitted,
    exit 0."""
    art = tmp_path / "chaos_artifact.json"
    cov = tmp_path / "COVERAGE.json"
    tl = tmp_path / "run.timeline.jsonl"
    rc = cli_main(["cli", "chaos", "--smoke",
                   "--models=throughput_sag",
                   f"--out={art}", f"--coverage-out={cov}",
                   f"--timeline={tl}"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "throughput_sag" in out
    doc = json.loads(art.read_text())
    assert doc["metric"] == "chaos_coverage"
    assert json.loads(cov.read_text()) == doc
    kinds = {json.loads(line).get("kind")
             for line in tl.read_text().splitlines()}
    assert kinds == {"chaos"}


def test_cli_chaos_unknown_model_exits_2(capsys):
    assert cli_main(["cli", "chaos", "--models=not_a_model"]) == 2
    capsys.readouterr()


def test_cli_coverage_renders_saved_matrix(tmp_path, capsys):
    p = tmp_path / "COVERAGE.json"
    p.write_text(json.dumps(_chaos_artifact(1.0)))
    assert cli_main(["cli", "coverage", str(p)]) == 0
    out = capsys.readouterr().out
    assert "bit_flip" in out and "chaos coverage" in out
    assert cli_main(["cli", "coverage",
                     str(tmp_path / "missing.json")]) == 2


def test_summarize_renders_chaos_coverage_rows(tmp_path):
    """scripts/summarize_bench.py renders per-model coverage rows
    (model, detection rate, p95 latency, MTTR, policy verdict) from a
    chaos artifact — the synthetic-artifact regression pin."""
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    p = tmp_path / "chaos_artifact.json"
    doc = _chaos_artifact(1.0)
    model = doc["context"]["chaos"]["models"]["bit_flip"]
    model["rollup"].update({"p95_detection_latency_seconds": 0.0123,
                            "mttr_seconds": 0.045,
                            "false_positive_rate": 0.0})
    model["policy"] = {"check_every": 8, "threshold_mode": "static",
                       "tier_config": "device", "evict": False}
    p.write_text(json.dumps(doc))
    out = subprocess.run(
        [sys.executable, "scripts/summarize_bench.py", str(p)],
        cwd=root, capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr[-500:]
    assert "chaos bit_flip" in out.stdout
    assert "det 1.00" in out.stdout
    assert "p95 0.0123s" in out.stdout
    assert "mttr 0.045s" in out.stdout
    assert "policy every=8/static" in out.stdout
