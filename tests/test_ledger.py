"""Run-ledger contract: hostile ingestion never raises, schema
migration, duplicate-key supersession, the extractor mirror pin, and
the seeded committed ledger.

The ledger's whole reason to exist is that the repo's real artifact
diet is hostile — BENCH_r01 is a driver wrapper whose ``parsed`` is
null, r02–r05 carry null headlines with kill reasons, MULTICHIP probes
have no metric at all — so most of this file feeds it garbage and
asserts it produces NAMED degradation rows instead of exceptions.
"""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

from ft_sgemm_tpu.perf import compare, ledger

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Hostile ingestion (ISSUE 10 satellite: nulls, missing stages, drift,
# duplicates)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("doc", [
    None,
    [],
    "a string",
    {},
    {"metric": "x"},
    {"metric": "abft_kernel_huge_gflops_4096", "value": None,
     "unit": "GFLOPS", "context": None},
    {"value": float("nan")},  # json-representable garbage value
    {"metric": 7, "value": True, "unit": 3.5, "context": {"errors": []}},
    {"parsed": None, "rc": 1, "cmd": "python bench.py", "tail": "boom"},
    {"context": {"run_report": "not a dict", "encode_modes": [1, 2],
                 "abft_tuned": {"gflops": "NaN?"}}},
])
def test_ingest_never_raises(doc):
    e = ledger.ingest(doc, run_id="hostile")
    assert e["schema"] == ledger.SCHEMA_VERSION
    assert e["run_id"] == "hostile"
    assert isinstance(e["degradations"], list)
    json.dumps(e)  # every entry must be JSON-serializable as produced


def test_null_artifact_gets_named_reason():
    doc = {"metric": "abft_kernel_huge_gflops_4096", "value": None,
           "unit": "GFLOPS",
           "context": {"errors": {"worker_rc":
                                  "killed (supervisor deadline reached)"}}}
    e = ledger.ingest(doc, run_id="r")
    assert e["value"] is None
    assert any(d.startswith("null_value:")
               and "deadline" in d for d in e["degradations"])


def test_wrapper_with_null_parsed_records_rc_and_tail():
    doc = {"n": 1, "cmd": "python bench.py", "rc": 1,
           "parsed": None, "tail": "x\nRuntimeError: backend dead\n"}
    e = ledger.ingest(doc, run_id="r01", source="BENCH_r01.json")
    assert e["kind"] == "bench"
    assert "worker_rc:1" in e["degradations"]
    assert "no_artifact_parsed" in e["degradations"]
    assert any("backend dead" in d for d in e["degradations"])


def test_partial_artifact_keeps_kill_metadata():
    doc = {"metric": "abft_kernel_huge_gflops_4096", "value": 123.0,
           "unit": "GFLOPS",
           "context": {"partial": True, "killed_at_stage": "ft_rowcol",
                       "completed_stages": ["ft_headline"]}}
    e = ledger.ingest(doc, run_id="r")
    assert e["partial"] is True
    assert e["killed_at_stage"] == "ft_rowcol"
    assert e["completed_stages"] == ["ft_headline"]
    assert any(d == "partial:ft_rowcol" for d in e["degradations"])
    assert e["value"] == 123.0  # partial still carries its salvage


def test_extractor_mirrors_compare_extract_stages():
    """perf/ledger.py cannot import perf/compare.py (stdlib/path-loadable
    constraint), so its measurement extractor is a MIRROR — this pin is
    what keeps the two from drifting."""
    doc = compare.load_artifact(os.path.join(REPO, "BASELINE_SMOKE.json"))
    assert ledger.extract_measurements(doc) == compare.extract_stages(doc)
    # And on a synthetic artifact exercising every extraction branch:
    doc2 = {"metric": "m", "value": 5.0,
            "context": {"a_gflops": 1.0, "b_gflops": None,
                        "abft_tuned": {"gflops": 2.0},
                        "encode_modes": {"vpu": {"seconds": 0.5},
                                         "mxu": "junk"},
                        "run_report": {"stages": [
                            {"name": "s1", "seconds": 0.1},
                            {"seconds": 0.2}, "junk"]}}}
    assert ledger.extract_measurements(doc2) == compare.extract_stages(doc2)


def test_schema_migration_from_v0(tmp_path):
    """A pre-ledger v0 line (run/rev keys, flat string platform) reads
    forward into the current schema, tagged."""
    path = tmp_path / "led.jsonl"
    v0 = {"run": "old1", "rev": "abc123", "platform": "tpu",
          "metric": "m", "value": 10.0}
    v1 = ledger.ingest({"metric": "m", "value": 11.0, "context": {}},
                       run_id="new1")
    future = dict(v1, run_id="future", schema=ledger.SCHEMA_VERSION + 1)
    with open(path, "w") as fh:
        for d in (v0, v1, future):
            fh.write(json.dumps(d) + "\n")
        fh.write("torn {\n")       # torn tail
        fh.write("[1, 2, 3]\n")    # foreign line
    entries = ledger.read_ledger(str(path))
    assert [e["run_id"] for e in entries] == ["old1", "new1", "future"]
    old = entries[0]
    assert old["schema"] == ledger.SCHEMA_VERSION
    assert old["git_rev"] == "abc123"
    assert old["platform"]["used"] == "tpu"
    assert old["value"] == 10.0
    assert "migrated_from_schema_0" in old["degradations"]
    assert any(d.startswith("schema_newer_than_reader")
               for d in entries[2]["degradations"])


def test_duplicate_run_ids_last_append_wins(tmp_path):
    path = tmp_path / "led.jsonl"
    for v in (1.0, 2.0):
        e = ledger.ingest(
            {"metric": "m", "value": v,
             "context": {"platform_used": "cpu", "device_kind": "cpu"}},
            run_id="dup")
        ledger.append(str(path), e)
    entries = ledger.read_ledger(str(path))
    assert len(entries) == 2  # append-only: both lines persist
    deduped = ledger.dedup_entries(entries)
    assert len(deduped) == 1  # read-side: last writer wins
    assert deduped[0]["value"] == 2.0
    # Same run_id on a DIFFERENT platform is a different ledger key.
    other = ledger.ingest(
        {"metric": "m", "value": 3.0,
         "context": {"platform_used": "tpu", "device_kind": "v5e"}},
        run_id="dup")
    ledger.append(str(path), other)
    assert len(ledger.dedup_entries(ledger.read_ledger(str(path)))) == 2


def test_append_roundtrip_and_history_render(tmp_path):
    path = tmp_path / "led.jsonl"
    for i, v in enumerate([None, 10.0]):
        ledger.append(str(path), ledger.ingest(
            {"metric": "m_gflops", "value": v, "unit": "GFLOPS",
             "context": ({"partial": True, "killed_at_stage": "huge"}
                         if v is None else {})},
            run_id=f"r{i}"))
    entries = ledger.read_ledger(str(path))
    text = ledger.format_history(entries)
    assert "r0" in text and "r1" in text
    assert "PARTIAL@huge" in text
    assert "10.0 GFLOPS" in text


# ---------------------------------------------------------------------------
# The committed seed + jax-free loading discipline
# ---------------------------------------------------------------------------


def test_committed_ledger_seeded_from_bench_history():
    """The committed LEDGER.jsonl carries the full r01–r05 trajectory
    (plus multichip probes and baselines) with named degradations —
    the acceptance artifact of the seeding satellite."""
    entries = ledger.read_ledger(os.path.join(REPO, "LEDGER.jsonl"))
    ids = {e["run_id"] for e in entries}
    for n in range(1, 6):
        assert f"BENCH_r0{n}" in ids, ids
        assert f"MULTICHIP_r0{n}" in ids, ids
    assert "BASELINE_HEADLINE" in ids and "BASELINE_SMOKE" in ids
    by_id = {e["run_id"]: e for e in entries}
    # r01 died before emitting; r05 emitted a null with a kill reason.
    assert "no_artifact_parsed" in by_id["BENCH_r01"]["degradations"]
    assert any(d.startswith("null_value:") and "deadline" in d
               for d in by_id["BENCH_r05"]["degradations"])
    assert by_id["BASELINE_HEADLINE"]["value"] == 25600.0
    assert by_id["BASELINE_SMOKE"]["measurements"]


def test_module_is_loadable_without_the_package(tmp_path):
    """timeline.py discipline: the bench supervisor loads ledger.py by
    file path in a process that must never import jax — the module must
    work standalone AND ingest a real committed artifact."""
    code = """
import importlib.util, json, sys
assert "jax" not in sys.modules
spec = importlib.util.spec_from_file_location("led", {led_path!r})
mod = importlib.util.module_from_spec(spec)
spec.loader.exec_module(mod)
assert "jax" not in sys.modules, "ledger.py pulled jax in"
e = mod.ingest_file({art_path!r})
assert e["run_id"] == "BENCH_r05"
mod.append({out_path!r}, e)
assert len(mod.read_ledger({out_path!r})) == 1
print("OK")
""".format(led_path=os.path.join(REPO, "ft_sgemm_tpu", "perf",
                                 "ledger.py"),
           art_path=os.path.join(REPO, "BENCH_r05.json"),
           out_path=str(tmp_path / "led.jsonl"))
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "OK" in proc.stdout


# ---------------------------------------------------------------------------
# CLI surfaces (ingest / history) + the regen script
# ---------------------------------------------------------------------------


def test_cli_ingest_and_history(tmp_path, capsys):
    from ft_sgemm_tpu.cli import main as cli_main

    led = str(tmp_path / "led.jsonl")
    art = tmp_path / "a.json"
    art.write_text(json.dumps({"metric": "m", "value": 1.5, "unit": "u",
                               "context": {"platform_used": "cpu"}}))
    rc = cli_main(["cli", "ingest", led, str(art),
                   str(os.path.join(REPO, "BENCH_r01.json"))])
    assert rc == 0
    rc = cli_main(["cli", "history", led])
    assert rc == 0
    out = capsys.readouterr().out
    assert "BENCH_r01" in out and "2 runs" in out
    rc = cli_main(["cli", "history", str(tmp_path / "missing.jsonl")])
    assert rc == 2


def test_regen_results_renders_ledger_section(tmp_path):
    led = str(tmp_path / "led.jsonl")
    for i, v in enumerate([None, 100.0, 110.0]):
        ledger.append(led, ledger.ingest(
            {"metric": "m", "value": v, "unit": "GFLOPS",
             "context": {"platform_used": "tpu", "device_kind": "v5e"}},
            run_id=f"r{i}"))
    results = tmp_path / "RESULTS.md"
    results.write_text("# hand-written narrative\n\nkeep me\n")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "regen_results.py"),
         led, str(results)], capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    text = results.read_text()
    assert "keep me" in text                      # narrative untouched
    assert "<!-- ledger:begin -->" in text
    assert "| r2 | " in text and "+10.0%" in text  # delta vs previous run
    # Idempotent + --check contract.
    proc2 = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "regen_results.py"),
         led, str(results), "--check"],
        capture_output=True, text=True, timeout=120)
    assert proc2.returncode == 0, proc2.stderr
    assert results.read_text() == text


def test_committed_results_ledger_section_is_current():
    """RESULTS.md's auto-generated block must match the committed
    ledger — the 'committed, reviewable artifact' half of the tentpole."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "regen_results.py"),
         "--check"], capture_output=True, text=True, timeout=120,
        cwd=REPO)
    assert proc.returncode == 0, proc.stderr + proc.stdout


def test_summarize_bench_ledger_delta_and_partial_row(tmp_path):
    led = str(tmp_path / "led.jsonl")
    ledger.append(led, ledger.ingest(
        {"metric": "abft_kernel_huge_gflops_4096", "value": 100.0,
         "unit": "GFLOPS", "context": {"platform_used": "tpu"}},
        run_id="prev"))
    art = tmp_path / "art.json"
    art.write_text(json.dumps(
        {"metric": "abft_kernel_huge_gflops_4096", "value": 80.0,
         "unit": "GFLOPS",
         "context": {"platform_used": "tpu", "partial": True,
                     "killed_at_stage": "ft_rowcol",
                     "completed_stages": ["ft_headline"]}}))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "summarize_bench.py"),
         str(art), f"--ledger={led}"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "-20.0% vs ledger run prev" in proc.stdout
    assert "PARTIAL@ft_rowcol" in proc.stdout


def test_bench_emit_appends_to_ledger_env(tmp_path, monkeypatch):
    """FT_SGEMM_LEDGER wiring in bench.py: the emitted artifact line
    also lands as a ledger row (exercised in-process via the loader the
    supervisor uses)."""
    spec = importlib.util.spec_from_file_location(
        "bench_for_ledger", os.path.join(REPO, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    led = str(tmp_path / "led.jsonl")
    monkeypatch.setenv("FT_SGEMM_LEDGER", led)
    monkeypatch.setenv("FT_SGEMM_LEDGER_RUN_ID", "unit-run")
    bench._ledger_append({"metric": "m", "value": 2.0, "unit": "u",
                          "context": {"platform_used": "cpu"}})
    entries = ledger.read_ledger(led)
    assert len(entries) == 1
    assert entries[0]["run_id"] == "unit-run"
    assert entries[0]["value"] == 2.0
