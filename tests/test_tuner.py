"""Autotuner subsystem (ft_sgemm_tpu.tuner): space, cache, dispatch.

Pins the subsystem's four contract points:

1. the candidate space is pruned by the calibrated VMEM model BEFORE any
   compile/measure work, and known-infeasible tiles never survive;
2. the cache round-trips: a tuned winner persists, loads back, and
   dispatch provably selects the cached block config (the lowered HLO of
   a tuned named-shape call is byte-identical to an explicit KernelShape
   call at the cached tile — grid/block introspection at its strongest);
3. corrupt / wrong-schema / invalid-entry cache files are ignored with a
   warning and dispatch falls back to heuristics;
4. zero-regression: with an empty or absent cache (or tuning disabled),
   the lowered HLO of the ft_sgemm and attention entry points is
   byte-identical to the heuristic path (the tests/test_telemetry.py
   pinning technique).
"""

import json
import warnings

import jax
import numpy as np
import pytest

import ft_sgemm_tpu as ft
from ft_sgemm_tpu import tuner
from ft_sgemm_tpu.configs import KernelShape
from ft_sgemm_tpu.ops.vmem import MIB, estimate_vmem_bytes
from ft_sgemm_tpu.tuner import cache as tcache


@pytest.fixture(autouse=True)
def _own_cache(tmp_path, monkeypatch):
    """Every test gets a private cache file and a clean memo."""
    monkeypatch.setenv(tcache.ENV_CACHE_PATH,
                       str(tmp_path / "tuner_cache.json"))
    tcache.clear_memo()
    yield
    tcache.clear_memo()


def _inputs(rng, m=256, n=256, k=256):
    return (rng.standard_normal((m, k)).astype(np.float32),
            rng.standard_normal((n, k)).astype(np.float32),
            rng.standard_normal((m, n)).astype(np.float32))


def _lower_ft(fn, a, b, c):
    return jax.jit(lambda a, b, c: fn(a, b, c).c).lower(a, b, c).as_text()


# -- space: enumeration + static pruning ------------------------------------


def test_space_prunes_vmem_infeasible_candidates():
    feasible, pruned = tuner.enumerate_space(
        4096, 4096, 4096, strategy="weighted", limit=16 * MIB)
    # The recorded round-4 OOM (weighted @ 512^3 f32, ~17.9 MiB predicted
    # by the calibrated model — tests/test_vmem.py) must be pruned, with
    # the reason naming the budget.
    assert all(s.block != (512, 512, 512) for s in feasible)
    reasons = {tuple(p.shape.block): p.reason for p in pruned}
    assert "VMEM" in reasons[(512, 512, 512)]
    # Everything that survived really is predicted to fit.
    for s in feasible:
        assert estimate_vmem_bytes(s, "weighted_precomp") <= 16 * MIB


def test_space_prunes_tiles_beyond_padded_problem():
    feasible, pruned = tuner.enumerate_space(256, 256, 256,
                                             strategy="weighted")
    assert all(max(s.block) <= 256 for s in feasible)
    assert any("padded problem" in p.reason for p in pruned)


def test_space_orders_best_guess_first():
    feasible, _ = tuner.enumerate_space(1024, 1024, 1024,
                                        strategy="weighted")
    # Biggest block volume first (the measurement budget spends itself on
    # likely winners).
    vols = [s.bm * s.bn * s.bk for s in feasible]
    assert vols[0] == max(vols)


# -- cache: round-trip, corruption, schema ----------------------------------


def test_cache_round_trip_and_dispatch_selects_cached_config(rng):
    a, b, c = _inputs(rng)
    key = tuner.make_key(256, 256, 256, strategy="weighted",
                         in_dtype="float32", injection_enabled=False)
    kfn = ft.make_ft_sgemm("huge")
    heuristic_hlo = _lower_ft(kfn, a, b, c)
    tcache.store(key, {"block": [128, 256, 256]})

    tuned_hlo = _lower_ft(kfn, a, b, c)
    explicit = ft.make_ft_sgemm(
        KernelShape("tuned_128x256x256", 128, 256, 256, (0,) * 7))
    explicit_hlo = _lower_ft(explicit, a, b, c)
    # Dispatch provably selected the cached tile: the tuned named-shape
    # call lowers to EXACTLY the explicit-KernelShape program at the
    # cached block (grid + block shapes included), and differs from the
    # heuristic program.
    assert tuned_hlo == explicit_hlo
    assert tuned_hlo != heuristic_hlo
    # ...and still computes the right answer.
    want = np.asarray(ft.sgemm_reference(a, b, c, 1.0, -1.5))
    got = np.asarray(kfn(a, b, c).c)
    np.testing.assert_allclose(got, want, atol=1e-3)


def test_lookup_tile_miss_and_disabled(monkeypatch):
    assert tuner.lookup_tile(256, 256, 256, strategy="weighted",
                             in_dtype="float32",
                             injection_enabled=False) is None
    key = tuner.make_key(256, 256, 256, strategy="weighted",
                         in_dtype="float32", injection_enabled=False)
    tcache.store(key, {"block": [128, 128, 128]})
    assert tuner.lookup_tile(256, 256, 256, strategy="weighted",
                             in_dtype="float32",
                             injection_enabled=False).block == (128, 128, 128)
    with tuner.override_disabled():
        assert tuner.lookup_tile(256, 256, 256, strategy="weighted",
                                 in_dtype="float32",
                                 injection_enabled=False) is None
    monkeypatch.setenv(tuner.ENV_TUNING, "0")
    assert tuner.lookup_tile(256, 256, 256, strategy="weighted",
                             in_dtype="float32",
                             injection_enabled=False) is None


def test_key_separates_injection_strategy_dtype():
    kws = dict(in_dtype="float32", injection_enabled=False)
    base = tuner.make_key(256, 256, 256, strategy="weighted", **kws)
    assert tuner.make_key(256, 256, 256, strategy="rowcol", **kws) != base
    assert tuner.make_key(256, 256, 256, strategy="weighted",
                          in_dtype="bfloat16",
                          injection_enabled=False) != base
    assert tuner.make_key(256, 256, 256, strategy="weighted",
                          in_dtype="float32",
                          injection_enabled=True) != base
    # Bucketing: nearby sizes share a key, far ones don't.
    assert tuner.make_key(250, 201, 256, strategy="weighted", **kws) == base
    assert tuner.make_key(512, 256, 256, strategy="weighted", **kws) != base


def test_corrupt_cache_ignored_with_warning(tmp_path, monkeypatch):
    path = tmp_path / "corrupt.json"
    path.write_text("{this is not json")
    monkeypatch.setenv(tcache.ENV_CACHE_PATH, str(path))
    tcache.clear_memo()
    with pytest.warns(UserWarning, match="corrupt"):
        assert tcache.load_entries() == {}
    # Memoized: the second read is silent (and still a miss).
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert tuner.lookup_tile(256, 256, 256, strategy="weighted",
                                 in_dtype="float32",
                                 injection_enabled=False) is None


def test_mismatched_schema_cache_ignored_with_warning(tmp_path, monkeypatch):
    path = tmp_path / "old.json"
    path.write_text(json.dumps({"schema": 999, "entries": {
        "k": {"block": [128, 128, 128]}}}))
    monkeypatch.setenv(tcache.ENV_CACHE_PATH, str(path))
    tcache.clear_memo()
    with pytest.warns(UserWarning, match="schema"):
        assert tcache.load_entries() == {}


def test_invalid_entry_dropped_with_warning(tmp_path, monkeypatch):
    path = tmp_path / "mixed.json"
    path.write_text(json.dumps({"schema": tcache.SCHEMA_VERSION, "entries": {
        "good": {"block": [128, 256, 128]},
        "bad": {"block": [100, 256, 128]},       # not a multiple of 128
        "worse": {"block": "512x512x512"}}}))
    monkeypatch.setenv(tcache.ENV_CACHE_PATH, str(path))
    tcache.clear_memo()
    with pytest.warns(UserWarning, match="invalid cache entry"):
        entries = tcache.load_entries()
    assert set(entries) == {"good"}


def test_store_rejects_illegal_block():
    with pytest.raises(ValueError, match="block"):
        tcache.store("k", {"block": [100, 128, 128]})


def test_store_is_atomic_and_merges(tmp_path, monkeypatch):
    path = tmp_path / "c.json"
    monkeypatch.setenv(tcache.ENV_CACHE_PATH, str(path))
    tcache.clear_memo()
    tcache.store("k1", {"block": [128, 128, 128]})
    tcache.store("k2", {"block": [256, 128, 128]})
    doc = json.loads(path.read_text())
    assert doc["schema"] == tcache.SCHEMA_VERSION
    assert set(doc["entries"]) == {"k1", "k2"}


# -- zero-regression: empty/absent cache -> byte-identical HLO ---------------


def test_no_cache_hlo_identical_ft_sgemm(rng):
    a, b, c = _inputs(rng)
    kfn = ft.make_ft_sgemm("huge")
    with tuner.override_disabled():
        baseline = _lower_ft(kfn, a, b, c)  # the heuristic-only path
    assert _lower_ft(kfn, a, b, c) == baseline, (
        "empty-cache tuned dispatch changed the ft_sgemm HLO")


def test_no_cache_hlo_identical_attention(rng):
    from ft_sgemm_tpu.ops.attention import make_ft_attention

    q = rng.standard_normal((128, 64)).astype(np.float32)
    k = rng.standard_normal((128, 64)).astype(np.float32)
    v = rng.standard_normal((128, 64)).astype(np.float32)
    attn = make_ft_attention()

    def lower():
        return jax.jit(lambda q, k, v: attn(q, k, v).out).lower(
            q, k, v).as_text()

    with tuner.override_disabled():
        baseline = lower()
    assert lower() == baseline, (
        "empty-cache tuned dispatch changed the attention HLO")


def test_attention_picks_cached_tile_for_default_shapes(rng):
    from ft_sgemm_tpu.ops.attention import make_ft_attention

    q = rng.standard_normal((128, 64)).astype(np.float32)
    k = rng.standard_normal((128, 64)).astype(np.float32)
    v = rng.standard_normal((128, 64)).astype(np.float32)
    attn = make_ft_attention()

    def lower():
        return jax.jit(lambda q, k, v: attn(q, k, v).out).lower(
            q, k, v).as_text()

    baseline = lower()
    # Seed the QK GEMM's key: (L, Lk, d) = (128, 128, 64) -> bucket
    # (128, 128, 128); beta=0 attention GEMMs, clean run.
    key = tuner.make_key(128, 128, 64, strategy="weighted",
                         in_dtype="float32", injection_enabled=False)
    tcache.store(key, {"block": [128, 128, 128]})
    assert lower() != baseline, (
        "seeded cache entry did not reach attention's QK/PV dispatch")
    # Caller-supplied explicit shapes are never overridden.
    custom = make_ft_attention(
        qk_shape=KernelShape("qk", 256, 256, 128, (0,) * 7),
        pv_shape=KernelShape("pv", 256, 128, 512, (0,) * 7))
    with tuner.override_disabled():
        custom_base = jax.jit(
            lambda q, k, v: custom(q, k, v).out).lower(q, k, v).as_text()
    assert jax.jit(lambda q, k, v: custom(q, k, v).out).lower(
        q, k, v).as_text() == custom_base


def test_explicit_shape_dispatch_never_tuned(rng):
    a, b, c = _inputs(rng)
    shape = KernelShape("sweep_tile", 256, 256, 256, (0,) * 7)
    kfn = ft.make_ft_sgemm(shape)
    baseline = _lower_ft(kfn, a, b, c)
    key = tuner.make_key(256, 256, 256, strategy="weighted",
                         in_dtype="float32", injection_enabled=False)
    tcache.store(key, {"block": [128, 128, 128]})
    assert _lower_ft(kfn, a, b, c) == baseline, (
        "explicit KernelShape dispatch consulted the tile cache")


# -- tune(): search + persist + telemetry ------------------------------------


def test_tune_persists_winner_and_dispatch_uses_it(rng):
    report = tuner.tune(128, budget=2, reps=1, samples=1,
                        method="interpret")
    assert report["best"] is not None
    assert report["heuristic"] is not None
    best_block = tuple(report["best"]["block"])
    tile = tuner.lookup_tile(128, 128, 128, strategy="weighted",
                             in_dtype="float32", injection_enabled=False)
    assert tile is not None and tile.block == best_block
    # The search itself must not have been served by the cache it wrote:
    # re-tuning with the entry present measures the same candidate list.
    report2 = tuner.tune(128, budget=2, reps=1, samples=1,
                         method="interpret")
    assert [r["block"] for r in report2["results"]] == \
        [r["block"] for r in report["results"]]


def test_tune_dry_run_measures_nothing(tmp_path, monkeypatch):
    path = tmp_path / "never_written.json"
    monkeypatch.setenv(tcache.ENV_CACHE_PATH, str(path))
    tcache.clear_memo()
    report = tuner.tune(512, dry_run=True)
    assert "results" not in report and "best" not in report
    assert report["feasible"] and report["pruned"]
    assert not path.exists()


def test_tune_records_through_telemetry_registry(rng):
    from ft_sgemm_tpu import telemetry

    telemetry.reset()
    telemetry.configure(None)
    try:
        tuner.tune(128, budget=1, reps=1, samples=1, method="interpret")
        reg = telemetry.get_registry()
        assert reg.total("tuner_measurements") >= 2  # heuristic + 1
        names = {s["name"] for s in reg.collect()}
        assert "tuner_candidate_gflops" in names
    finally:
        telemetry.reset()


# -- CLI: tune / tune-show round-trip ----------------------------------------


def test_cli_tune_roundtrips_via_tune_show(capsys):
    from ft_sgemm_tpu import cli

    rc = cli.main(["cli", "tune", "128", "--budget=1", "--reps=1",
                   "--samples=1"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "cache written:" in out
    assert "heuristic" in out and "best" in out

    rc = cli.main(["cli", "tune-show"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "1 entries" in out or "2 entries" in out
    assert "weighted|enc=vpu|thr=static|inj=0" in out


def test_cli_tune_dry_run(capsys):
    from ft_sgemm_tpu import cli

    rc = cli.main(["cli", "tune", "512", "--dry-run"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "dry run: nothing measured" in out
    assert "feasible" in out

    rc = cli.main(["cli", "tune-show"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "0 entries" in out


def test_cli_tune_rejects_bad_args(capsys):
    from ft_sgemm_tpu import cli

    assert cli.main(["cli", "tune", "x"]) == 2
    assert cli.main(["cli", "tune", "128", "256"]) == 2
    assert cli.main(["cli", "tune", "--strategy=warp"]) == 2
    assert cli.main(["cli", "tune", "--method=magic"]) == 2
    capsys.readouterr()
