"""Fleet runtime: launcher kill-safety, DCN-honest planes, host eviction.

Two kinds of coverage, both CPU-only tier-1:

- REAL multi-process: ``launch_fleet`` spawns actual OS processes that
  form a 2-proc x 4-vdev ``jax.distributed`` mesh (gloo CPU
  collectives), so the cross-process assertions — staged-vs-flat
  counter equality, ``inject_coords`` localization, global-tier
  detection of in-flight DCN corruption, the merged fleet view naming
  both ranks — run across a process boundary that actually exists.
  The worker programs assert SPMD-side; these tests assert the
  collected report.
- In-process: the pieces with no collective in them (slot formation,
  the dispatcher's migrate-on-evict, host-granularity blame, the live
  shard merge) tested directly.
"""

import json
import threading
import time

import numpy as np
import pytest

from ft_sgemm_tpu.fleet.dispatch import FleetDispatcher, HostSlot
from ft_sgemm_tpu.fleet.launch import FleetSpec, launch_fleet
from ft_sgemm_tpu.parallel import make_multihost_mesh, multihost_ft_sgemm
from ft_sgemm_tpu.parallel.multihost import _host_slots
from ft_sgemm_tpu.resilience import (ElasticController, EvictionPolicy,
                                     surviving_mesh)
from ft_sgemm_tpu.telemetry.aggregate import LiveAggregator
from ft_sgemm_tpu.telemetry.events import FaultEvent
from ft_sgemm_tpu.utils import generate_random_matrix, verify_matrix


class FakeDev:
    """Stand-in with the two attributes slot formation keys on."""

    def __init__(self, process_index, devid):
        self.process_index = process_index
        self.id = devid

    def __repr__(self):
        return f"dev(p{self.process_index},{self.id})"


# ---------------------------------------------------------------------------
# Slot formation (satellite: hosts= multiples of process_count)
# ---------------------------------------------------------------------------


def _fake_fleet(counts):
    """Devices of len(counts) processes with non-contiguous global ids
    (process p's ids start at p*131072 — the real TFRT spacing)."""
    devs = []
    for p, n in enumerate(counts):
        devs.extend(FakeDev(p, p * 131072 + i) for i in range(n))
    return devs


def test_host_slots_subdivides_processes_contiguously():
    devs = _fake_fleet((4, 4))
    slots = _host_slots(devs, 4, 2)
    assert len(slots) == 4
    for slot in slots:
        procs = {d.process_index for d in slot}
        assert len(procs) == 1, slot
    # Contiguous within each process, processes in order.
    assert [d.id for d in slots[0]] == [0, 1]
    assert [d.id for d in slots[1]] == [2, 3]
    assert [d.id for d in slots[2]] == [131072, 131073]


def test_host_slots_uneven_counts_work_when_divisible():
    # (2, 6) devices: hosts=4 (per_host=2) subdivides each process
    # cleanly even though a flat reshape of the sorted list would put
    # one slot astride the process boundary.
    devs = _fake_fleet((2, 6))
    slots = _host_slots(devs, 4, 2)
    assert [len(s) for s in slots] == [2, 2, 2, 2]
    for slot in slots:
        assert len({d.process_index for d in slot}) == 1, slot


def test_host_slots_error_names_the_remedy():
    # (2, 6) with hosts=2 (per_host=4): process 0's 2 devices cannot
    # fill a 4-device slot — the error must say so and name hosts=
    # process_count as the way out.
    devs = _fake_fleet((2, 6))
    with pytest.raises(ValueError, match="hosts=jax.process_count"):
        _host_slots(devs, 2, 4)


def test_mesh_hosts_multiple_of_process_count_single_process():
    # Single process, 8 vdevs: any hosts= that divides 8 must build —
    # the satellite's cross-PROCESS variant is pinned by the launched
    # counters program (mesh_multiple in its report).
    for hosts in (1, 2, 4, 8):
        mesh = make_multihost_mesh(hosts=hosts)
        assert mesh.shape["host"] == hosts
        assert int(np.prod(tuple(mesh.shape.values()))) == 8


# ---------------------------------------------------------------------------
# multihost_ft_sgemm variant kwargs (satellite)
# ---------------------------------------------------------------------------


def test_multihost_variant_kwargs_and_local_shard_tuning(monkeypatch):
    seen = []

    def fake_lookup(m, n, k, **kw):
        seen.append((m, n, k))
        return (None, None)

    monkeypatch.setattr("ft_sgemm_tpu.tuner.lookup_winner", fake_lookup)
    mesh = make_multihost_mesh(hosts=2, ici_axes=(2, 2))
    m, n, k = 512, 128, 256
    rng = np.random.default_rng(5)
    a = generate_random_matrix(m, k, rng=rng)
    b = generate_random_matrix(n, k, rng=rng)
    c = generate_random_matrix(m, n, rng=rng)
    res = multihost_ft_sgemm(a, b, c, mesh, "huge", alpha=1.0, beta=-1.5,
                             encode="mxu", threshold="adaptive")
    want = (a.astype(np.float64) @ b.astype(np.float64).T
            - 1.5 * c.astype(np.float64)).astype(np.float32)
    ok, nbad, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
    assert ok, nbad
    # lookup_winner fired at trace time with the LOCAL shard problem:
    # M/(host*x)=128, K/y=128 — never the 512x256 global shape.
    assert seen, "tuner lookup never consulted"
    assert all(s == (128, 128, 128) for s in seen), seen


# ---------------------------------------------------------------------------
# Launcher: spawn/collect and kill-salvage on REAL processes
# ---------------------------------------------------------------------------


def test_fleet_wedge_killed_by_name_and_salvaged(tmp_path):
    t0 = time.monotonic()
    report = launch_fleet(FleetSpec(
        procs=2, vdevs=4, program="wedge", workdir=str(tmp_path / "w"),
        wedge_after=2.0, deadline_seconds=60.0,
        program_args={"wedge_sleep": 300.0}))
    assert not report["ok"]
    assert time.monotonic() - t0 < 45.0, "wedge kill must not wait it out"
    for rank in (0, 1):
        info = report["ranks"][rank]
        # Named degradation: the rank is WEDGED (not failed/deadline),
        # and what it completed before going silent was salvaged.
        assert info["status"] == "wedged"
        assert info["heartbeats"] == 2
        assert info["result"] is None
        assert info["salvage"]["stage_values"]["wedge_warmup"] == {
            "beats": 2}


def test_fleet_counters_two_real_processes(tmp_path):
    report = launch_fleet(FleetSpec(
        procs=2, vdevs=4, program="counters",
        workdir=str(tmp_path / "c"), deadline_seconds=420.0,
        wedge_after=180.0))
    assert report["ok"], report["ranks"]
    assert all(info["status"] == "ok"
               for info in report["ranks"].values())
    facts = report["result"]
    assert facts["process_count"] == 2
    # Staged counter reduction equals the flat psum across a REAL
    # process boundary.
    assert facts["staged_equals_flat"], (facts["staged"], facts["flat"])
    # Cross-process inject_coords localization: the merged view blames
    # exactly the (host, device) the injection named — on the rank the
    # coordinator cannot address.
    assert facts["localized"]["host"] == 1
    assert facts["localized"]["coords"] == [1, 0, 0]
    assert facts["localized"]["detected"] >= 1
    # In-flight DCN corruption detected at — only at — the global tier.
    assert facts["dcn_tier"] == "global"
    # The live merge covered both ranks' devices.
    assert facts["merged_hosts"] == [0, 1]
    assert facts["merged_devices"] == 8
    assert any(lbl.startswith("host1:") for lbl in facts["health_labels"])


def test_fleet_trace_join_two_real_processes(tmp_path):
    """ISSUE 20 acceptance, tier-1 shape: a REAL 2-proc launch of the
    ``trace`` program must show one trace_id on BOTH sides of the wire
    and a merged Perfetto trace whose flows cross process rows with
    skew-corrected monotone hops."""
    from ft_sgemm_tpu.telemetry import traceview

    workdir = tmp_path / "t"
    report = launch_fleet(FleetSpec(
        procs=2, vdevs=2, program="trace", workdir=str(workdir),
        deadline_seconds=420.0, wedge_after=180.0))
    assert report["ok"], report["ranks"]
    serve = report["result"]["serve"]
    tids = serve["trace"]["retried_trace_ids"]
    assert tids, serve["trace"]
    # The coordinator kept the retried ids; the remote rank's own
    # timeline carries the SAME ids on its execute and retry points —
    # the trace context really crossed the TCP hop.
    recs = [json.loads(line) for line in
            (workdir / "rank1" / "timeline.jsonl").read_text(
                encoding="utf-8").splitlines() if line.strip()]
    remote_ids = {r.get("trace_id") for r in recs if r.get("trace_id")}
    joined = set(tids) & remote_ids
    assert joined, (tids, sorted(remote_ids)[:5])
    assert any(r.get("trace_id") in joined
               and str(r.get("name", "")).endswith(":retry")
               for r in recs), "remote retry point must carry the id"
    # The dispatcher measured the remote host's clock skew over the
    # SAME connection the requests rode.
    skew = report["result"]["fleet"]["clock_skew_seconds"]
    assert "1" in skew and isinstance(skew["1"], float), skew
    # The run's economics accounted the forced retries: overhead
    # breakdown shares one denominator with the useful fraction.
    econ = report["result"]["fleet"]["economics"]
    assert econ["useful_flops_fraction"] is not None
    assert econ["overhead_fractions"]["retry"] > 0
    total = econ["useful_flops_fraction"] + sum(
        v for v in econ["overhead_fractions"].values() if v)
    assert abs(total - 1.0) < 1e-4, econ

    # ONE merged Perfetto document: supervisor + both ranks as separate
    # trace processes, flows joining hops across them.
    trace, path = traceview.merge_fleet(str(workdir))
    assert path == str(workdir / "fleet.trace.json")
    meta = trace["otherData"]
    assert meta["ranks"] == [0, 1]
    assert meta["processes"] >= 3, meta  # supervisor + 2 ranks
    assert meta["cross_process_flows"] >= 1, meta
    ev = trace["traceEvents"]
    ts_all = [e["ts"] for e in ev if e.get("ph") != "M"]
    assert ts_all == sorted(ts_all) and all(t >= 0 for t in ts_all)
    rank0_pid = traceview.PID + 1
    for tid in joined:
        hops = [e for e in ev
                if e.get("ph") in ("s", "t", "f") and e.get("id") == tid]
        assert len(hops) >= 2, tid
        assert len({h["pid"] for h in hops}) >= 2, hops
        # Skew-corrected order: the coordinator's submit is the flow
        # SOURCE; the remote hops follow it in corrected time.
        assert hops[0]["pid"] == rank0_pid, hops
        assert "submit" in hops[0]["args"]["hop"], hops[0]


# ---------------------------------------------------------------------------
# Dispatcher: placement, blame, migrate-on-evict (in-process)
# ---------------------------------------------------------------------------


def _slot(host, runner, **kw):
    kw.setdefault("workers", 1)
    return HostSlot(host=host, runner=runner, **kw)


def test_dispatcher_evict_host_migrates_queued_requests():
    release = threading.Event()
    served = {0: 0, 1: 0}
    lock = threading.Lock()

    def local(spec):
        with lock:
            served[0] += 1
        return {"ok": True, "host": 0, "spec": spec}

    def remote(spec):
        release.wait(timeout=30.0)
        with lock:
            served[1] += 1
        return {"ok": True, "host": 1, "spec": spec}

    d = FleetDispatcher(
        [_slot(0, local, host_tier="local", dcn_distance=0.0),
         _slot(1, remote, host_tier="dcn", dcn_distance=1.0)],
        placement="round_robin")
    try:
        futs = [d.submit({"i": i}) for i in range(6)]
        # host 1's single worker is blocked inside its first request;
        # its remaining queued requests must MIGRATE on eviction, not
        # drain on the evicted host.
        deadline = time.monotonic() + 10.0
        while d.stats()["per_host"][1]["inflight"] == 0 \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        facts = d.evict_host(1, reason="host_blame")
        assert facts["action"] == "evicted"
        assert facts["migrated"] >= 1
        assert facts["surviving_hosts"] == 1
        release.set()
        replies = [f.result(timeout=30.0) for f in futs]
        assert all(r["ok"] for r in replies)
        # Everything except the one request host 1 already held runs on
        # the survivor.
        assert served[1] == 1
        assert served[0] == 5
        assert d.stats()["evicted_hosts"] == [1]
        # Post-eviction traffic never names the evicted host again.
        assert d.submit({"i": 99}).result(timeout=30.0)["host"] == 0
        d.evict_host(0)
        with pytest.raises(RuntimeError, match="every host is evicted"):
            d.submit({"i": 100})
    finally:
        release.set()
        d.stop()


def test_dispatcher_stats_requests_hops_and_skew():
    """ISSUE 20 satellite: stats() reports per-slot request counts,
    hop-latency percentile estimates from the single registry stats
    path, and the last measured clock skew per remote host."""
    from ft_sgemm_tpu.telemetry import MetricsRegistry

    def local(spec):
        return {"ok": True, "host": 0, "seconds": 0.001}

    def remote(spec):
        return {"ok": True, "host": 1, "seconds": 0.004,
                "retry_seconds": 0.002,
                "wire": {"rtt_seconds": 0.003,
                         "remote_queue_seconds": 0.0005,
                         "skew_seconds": -0.25}}

    reg = MetricsRegistry()
    d = FleetDispatcher(
        [_slot(0, local, host_tier="local", dcn_distance=0.0),
         _slot(1, remote, host_tier="dcn", dcn_distance=1.0)],
        placement="round_robin", registry=reg)
    try:
        futs = [d.submit({"i": i}) for i in range(6)]
        assert all(f.result(timeout=30.0)["ok"] for f in futs)
        st = d.stats()
        assert st["per_host"][0]["requests"] == 3
        assert st["per_host"][1]["requests"] == 3
        assert st["per_host"][1]["clock_skew_seconds"] == -0.25
        # Local slot: no wire handshake, skew pinned at zero.
        assert st["per_host"][0]["clock_skew_seconds"] == 0.0
        hops = st["per_host"][1]["hop_percentiles"]
        # Every taxonomy hop the reply carried has a percentile row...
        for name in ("queue_wait", "rtt", "remote_queue",
                     "remote_execute", "retry"):
            assert hops[name]["p95"] >= 0, name
        # ...estimated from the SAME histogram buckets /metrics exports.
        from ft_sgemm_tpu.telemetry.registry import to_prometheus
        text = to_prometheus(reg.collect())
        assert "fleet_hop_rtt_seconds_bucket" in text
        assert 'fleet_clock_skew_seconds{host="1"} -0.25' in text
        # The local slot never fabricates wire hops.
        assert "rtt" not in st["per_host"][0].get("hop_percentiles", {})
    finally:
        d.stop()


def test_host_blame_decision_and_record():
    controller = ElasticController(EvictionPolicy(
        host_blame_limit=3, min_surviving_hosts=1))
    assert controller.should_evict_host(total_hosts=2) is None
    controller.note_device_blame(1, "TFRT_CPU_131072")
    controller.note_device_blame(1, "TFRT_CPU_131073")
    assert controller.should_evict_host(total_hosts=2) is None
    total = controller.note_device_blame(1, "TFRT_CPU_131072")
    assert total == 3
    decision = controller.should_evict_host(total_hosts=2)
    assert decision == (1, "host_blame")
    # Handed out at most once while the eviction is in flight.
    assert controller.should_evict_host(total_hosts=2) is None
    controller.record_host_eviction({"host": 1, "action": "evicted"})
    assert controller.host_evictions[-1]["host"] == 1
    assert controller.host_blames(1) == {"TFRT_CPU_131072": 2,
                                         "TFRT_CPU_131073": 1}
    # The fleet never shrinks below min_surviving_hosts.
    controller.note_device_blame(0, "TFRT_CPU_0")
    controller.note_device_blame(0, "TFRT_CPU_0")
    controller.note_device_blame(0, "TFRT_CPU_0")
    assert controller.should_evict_host(
        total_hosts=2, evicted_hosts=(1,)) is None


def test_surviving_mesh_exclude_hosts():
    import jax

    devs = list(jax.devices())
    # No device belongs to process 5: the mesh keeps all 8.
    mesh = surviving_mesh(devices=devs, exclude_hosts=(5,))
    assert int(np.prod(tuple(mesh.shape.values()))) == 8
    # Everything is process 0 single-process: evicting host 0 leaves
    # nothing, and that is an honest error, not an empty mesh.
    with pytest.raises(ValueError, match="no devices left"):
        surviving_mesh(devices=devs, exclude_hosts=(0,))
    # Device + host exclusion compose; survivors round down to the
    # largest power of two (7 -> 4).
    mesh = surviving_mesh(exclude=devs[0], devices=devs,
                          exclude_hosts=(5,))
    assert int(np.prod(tuple(mesh.shape.values()))) == 4


# ---------------------------------------------------------------------------
# Live aggregate merge (in-process)
# ---------------------------------------------------------------------------


def _event_line(detected, device, host=None, coords=None):
    devices = [{"host": host, "device": device, "id": 0,
                "coords": coords or [0, 0, 0],
                "axes": ["host", "x", "y"],
                "detected": detected, "uncorrectable": 0}]
    return FaultEvent(outcome="corrected", op="t", detected=detected,
                      corrected=detected, host=host,
                      devices=devices).to_json()


def test_live_aggregator_monotone_merge_and_torn_lines(tmp_path):
    s0 = tmp_path / "rank0.jsonl"
    s1 = tmp_path / "rank1.jsonl"
    agg = LiveAggregator()
    agg.add_shard(s0, host=0)
    agg.add_shard(s1, host=1)  # does not exist yet: polled silently
    assert agg.poll() == 0

    s0.write_text(_event_line(1, "TFRT_CPU_0", host=0) + "\n")
    assert agg.poll() == 1
    counts = [agg.fleet_view()["events"]]

    # A torn tail (no newline) is NOT consumed...
    line1 = _event_line(2, "TFRT_CPU_131072", host=1,
                        coords=[1, 0, 0])
    with open(s1, "w", encoding="utf-8") as fh:
        fh.write(line1[: len(line1) // 2])
    assert agg.poll() == 0
    counts.append(agg.fleet_view()["events"])
    # ...and is delivered exactly once when completed.
    with open(s1, "a", encoding="utf-8") as fh:
        fh.write(line1[len(line1) // 2:] + "\n")
    assert agg.poll() == 1
    assert agg.poll() == 0
    counts.append(agg.fleet_view()["events"])
    assert counts == sorted(counts), "merged view must be monotone"

    view = agg.fleet_view()
    assert sorted(view["hosts"]) == [0, 1]
    assert view["ranks"] == [0, 1]
    assert view["devices"][(1, "TFRT_CPU_131072")]["detected"] == 2

    # The merge feeds device_health across hosts, incrementally.
    from ft_sgemm_tpu.telemetry.monitor import DeviceHealthTracker

    tracker = DeviceHealthTracker()
    assert agg.feed_health(tracker) == 2
    assert agg.feed_health(tracker) == 0  # nothing new since last feed
    rows = tracker.rows()
    assert rows["host1:TFRT_CPU_131072"]["detected"] == 2
    assert rows["host0:TFRT_CPU_0"]["detected"] == 1


def test_live_aggregator_host_fallback_for_unattributed_events(tmp_path):
    shard = tmp_path / "r.jsonl"
    shard.write_text(json.dumps(
        {"outcome": "corrected", "op": "t", "detected": 1,
         "corrected": 1, "device": "TFRT_CPU_0"}) + "\n")
    agg = LiveAggregator()
    agg.add_shard(shard, host=3)
    agg.poll()
    # The event itself carried no host: the shard's declared rank is
    # applied so the merged table still attributes it.
    assert (3, "TFRT_CPU_0") in agg.device_table()["devices"]
